#!/usr/bin/env python
"""Train through a user-defined numpy operator (reference:
``example/numpy-ops/custom_softmax.py``): a softmax-with-loss head
written as ``CustomOp``/``CustomOpProp`` in plain numpy, registered
with ``mx.operator.register``, and used INSIDE a Symbol graph trained
by Module.

On TPU the forward/backward run as ``jax.pure_callback``s at the right
points of the compiled step — the callback contract the reference
implements with a custom-op thread pool (custom-inl.h:50).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


class NumpySoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], mx.nd.array(
            e / e.sum(axis=1, keepdims=True)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # fused softmax+CE gradient: (p - onehot(y)) / batch
        p = out_data[0].asnumpy()
        y = in_data[1].asnumpy().astype(int)
        g = p.copy()
        g[np.arange(len(y)), y] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(g / len(y)))
        self.assign(in_grad[1], req[1], mx.nd.zeros_like(in_data[1]))


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    n, n_cls = 512, 4
    X = rng.uniform(0, 1, (n, 16)).astype(np.float32)
    Y = rng.randint(0, n_cls, (n,)).astype(np.float32)
    X[np.arange(n), Y.astype(int)] += 2.0  # separable

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=n_cls, name="fc2")
    net = mx.sym.Custom(net, label, op_type="numpy_softmax",
                        name="softmax")

    it = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier(),
            eval_metric="acc")

    it.reset()
    metric = mx.metric.Accuracy()
    mod.score(it, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    print("final accuracy: %.3f" % acc, flush=True)
    if acc < 0.9:
        raise SystemExit("custom-op training failed to converge")
    print("CUSTOM_OP_OK", flush=True)


if __name__ == "__main__":
    main()
