#!/usr/bin/env python
"""Distributed data-parallel training (reference:
``example/distributed_training/cifar10_dist.py``).

Launch with the local emulation harness::

    python -m mxnet_tpu.tools.launch -n 2 --platform cpu -- \
        python example/distributed_training/cifar10_dist.py --num-epochs 2

Each worker trains on its shard and synchronizes gradients through
kvstore ``dist_sync`` (XLA collectives over ICI on a real pod, gloo on
the CPU harness).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--num-examples", type=int, default=1024)
    args = ap.parse_args()

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    print("worker %d/%d starting" % (rank, nw), flush=True)

    # per-worker shard of a synthetic CIFAR-shaped task (deterministic
    # across workers, sharded like ImageRecordIter part_index/num_parts)
    rng = np.random.RandomState(7)
    X = rng.uniform(0, 0.3, (args.num_examples, 3, 32, 32)) \
        .astype(np.float32)
    Y = rng.randint(0, 10, (args.num_examples,)).astype(np.float32)
    X += (Y * 0.07)[:, None, None, None]
    shard = args.num_examples // nw
    Xs = X[rank * shard:(rank + 1) * shard]
    Ys = Y[rank * shard:(rank + 1) * shard]
    it = mx.io.NDArrayIter(Xs, Ys, args.batch_size, shuffle=True)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.num_epochs):
        it.reset()
        metric.reset()
        for batch in it:
            with mx.autograd.record():
                out = net(batch.data[0])
                loss = loss_fn(out, batch.label[0])
            loss.backward()
            trainer.step(args.batch_size)
            metric.update(batch.label, [out])
        print("worker %d epoch %d %s" % (rank, epoch, metric.get()),
              flush=True)
    print("worker %d done" % rank, flush=True)


if __name__ == "__main__":
    main()
