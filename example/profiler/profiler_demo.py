#!/usr/bin/env python
"""Profiler walkthrough (reference: ``example/profiler/profiler_matmul.py``):
profile a training loop, annotate phases with the object API
(Domain/Task/Frame/Counter/Marker), dump a chrome trace and print the
aggregate per-op table.
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, profiler  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--out", type=str, default=None,
                    help="trace json path (default: temp file)")
    args = ap.parse_args()
    out = args.out or os.path.join(tempfile.mkdtemp(), "profile.json")

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(256, activation="relu"), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(64, 32).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, (64,)))

    profiler.set_config(profile_all=True, filename=out)
    profiler.set_state("run")

    domain = profiler.ProfileDomain("train_demo")
    frame = profiler.Frame(domain, "iteration")
    counter = profiler.Counter(domain, "steps_done")

    for i in range(args.steps):
        with frame:
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(64)
        counter.increment()
    loss.wait_to_read()
    profiler.Marker(domain, "train_done").mark("process")

    print(profiler.dumps(format="table"), flush=True)
    profiler.set_state("stop")
    profiler.dump()

    with open(out) as f:
        events = json.load(f)["traceEvents"]
    cats = {e.get("cat") for e in events}
    n_ops = sum(1 for e in events if e.get("cat") == "operator")
    print("trace: %s (%d events, %d operator spans, cats=%s)"
          % (out, len(events), n_ops, sorted(c for c in cats if c)),
          flush=True)
    assert n_ops > 0 and "frame" in cats and "counter" in cats
    print("PROFILER_OK", flush=True)


if __name__ == "__main__":
    main()
