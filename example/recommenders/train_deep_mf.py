#!/usr/bin/env python
"""Deep matrix-factorization recommender (reference:
``example/recommenders`` — matrix factorization + deep "neural MF"
variants on MovieLens, scaled to a zero-egress task).

NeuMF-style two-branch model: a GMF branch (elementwise product of user
and item embeddings) and an MLP branch (concat of a second embedding
pair through dense layers) fused into one score head, trained on
implicit feedback with sampled negatives (BCE).  The synthetic taste
model gives each user and item latent cluster identities; a user likes
items of their cluster with high probability.  Metric: hit@5 against 20
sampled negatives — must beat the random floor (0.25) decisively.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

USERS, ITEMS, K = 200, 400, 8      # K latent clusters
DIM = 16


def make_interactions(rng, n):
    ucl = rng.randint(0, K, USERS)
    icl = rng.randint(0, K, ITEMS)
    users, items, labels = [], [], []
    for _ in range(n):
        u = rng.randint(USERS)
        if rng.rand() < 0.5:  # positive: an item of the user's cluster
            cand = np.where(icl == ucl[u])[0]
            it = int(cand[rng.randint(len(cand))]) if len(cand) else \
                rng.randint(ITEMS)
            lab = 1.0 if len(cand) else 0.0
        else:                 # negative: random item, other cluster
            it = rng.randint(ITEMS)
            lab = 1.0 if icl[it] == ucl[u] else 0.0
        users.append(u)
        items.append(it)
        labels.append(lab)
    return (np.asarray(users, np.float32), np.asarray(items, np.float32),
            np.asarray(labels, np.float32), ucl, icl)


class NeuMF(gluon.nn.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.u_gmf = gluon.nn.Embedding(USERS, DIM)
            self.i_gmf = gluon.nn.Embedding(ITEMS, DIM)
            self.u_mlp = gluon.nn.Embedding(USERS, DIM)
            self.i_mlp = gluon.nn.Embedding(ITEMS, DIM)
            self.h1 = gluon.nn.Dense(32, activation="relu")
            self.h2 = gluon.nn.Dense(16, activation="relu")
            self.score = gluon.nn.Dense(1)

    def forward(self, u, i):
        gmf = self.u_gmf(u) * self.i_gmf(i)
        mlp = self.h2(self.h1(mx.nd.concat(self.u_mlp(u),
                                           self.i_mlp(i), dim=1)))
        return self.score(mx.nd.concat(gmf, mlp, dim=1))[:, 0]


def hit_at_5(net, rng, ucl, icl, trials=200):
    hits = 0
    for _ in range(trials):
        u = rng.randint(USERS)
        pos_items = np.where(icl == ucl[u])[0]
        if not len(pos_items):
            continue
        pos = int(pos_items[rng.randint(len(pos_items))])
        negs = rng.choice(np.where(icl != ucl[u])[0], 20, replace=False)
        cand = np.concatenate([[pos], negs]).astype(np.float32)
        uu = np.full(len(cand), u, np.float32)
        with autograd.pause():
            s = net(mx.nd.array(uu), mx.nd.array(cand)).asnumpy()
        if 0 in np.argsort(-s)[:5]:
            hits += 1
    return hits / trials


def train(epochs=6, batch=128, lr=0.01, seed=0, verbose=True):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    users, items, labels, ucl, icl = make_interactions(rng, 8000)
    net = NeuMF()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    for ep in range(epochs):
        perm = rng.permutation(len(users))
        tot = 0.0
        for i in range(0, len(users), batch):
            idx = perm[i:i + batch]
            with autograd.record():
                s = net(mx.nd.array(users[idx]),
                        mx.nd.array(items[idx]))
                loss = bce(s, mx.nd.array(labels[idx])).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        if verbose:
            print("epoch %d loss %.3f hit@5 %.3f"
                  % (ep, tot / max(1, len(users) // batch),
                     hit_at_5(net, np.random.RandomState(7), ucl, icl,
                              trials=60)))
    return net, hit_at_5(net, np.random.RandomState(7), ucl, icl)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    net, hit = train(epochs=args.epochs, verbose=not args.smoke)
    print("hit@5 vs 20 negatives: %.3f" % hit)
    if args.smoke:
        assert hit > 0.6, hit  # random floor ~5/21 = 0.24
        print("OK")


if __name__ == "__main__":
    main()
