#!/usr/bin/env python
"""Word embeddings with noise-contrastive estimation (reference:
``example/nce-loss/`` — wordvec.py/toy_nce.py: train a large-vocab
output layer without the full softmax).

Skip-gram on a synthetic zipfian corpus with planted co-occurrence
structure (words i and i^1 co-occur — zero-egress stand-in for text8).
The NCE head scores the true context word against k noise samples drawn
from the unigram distribution, so the cost per step is O(k) instead of
O(vocab); a full-softmax head trains alongside as the oracle.  The
smoke test asserts (a) NCE loss falls, (b) planted word pairs end up
with higher cosine similarity than random pairs.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

VOCAB = 2000
DIM = 32
K = 16  # noise samples per positive


def synthetic_corpus(n_pairs, seed=0):
    """(center, context) pairs: zipfian centers, context = center ^ 1
    with prob 0.7 else random — the planted structure to recover."""
    rng = np.random.RandomState(seed)
    ranks = np.arange(1, VOCAB + 1)
    probs = (1.0 / ranks) / (1.0 / ranks).sum()
    centers = rng.choice(VOCAB, size=n_pairs, p=probs)
    noise = rng.choice(VOCAB, size=n_pairs, p=probs)
    coin = rng.rand(n_pairs) < 0.7
    contexts = np.where(coin, centers ^ 1, noise)
    return centers.astype(np.int32), contexts.astype(np.int32), probs


class NCEWordVec(gluon.nn.Block):
    def __init__(self, noise_logp, **kw):
        super().__init__(**kw)
        self._noise_logp = noise_logp  # log(K * P_noise(w)), [VOCAB]
        with self.name_scope():
            self.in_embed = gluon.nn.Embedding(VOCAB, DIM)
            self.out_embed = gluon.nn.Embedding(VOCAB, DIM)
            self.out_bias = gluon.nn.Embedding(VOCAB, 1)

    def forward(self, center, samples, labels):
        """center [B]; samples [B, 1+K] (true context first);
        labels [B, 1+K] (1 for the true slot).  Returns per-slot
        sigmoid-CE — the NCE objective with the standard
        log(K*P_noise) normalizer, so the per-word bias absorbs
        frequency and the embeddings are left to encode co-occurrence."""
        e = self.in_embed(center)                    # [B, D]
        w = self.out_embed(samples)                  # [B, 1+K, D]
        b = self.out_bias(samples)[:, :, 0]          # [B, 1+K]
        norm = self._noise_logp[samples.asnumpy()]   # host gather
        logits = (w * e.expand_dims(1)).sum(axis=2) + b \
            - mx.nd.array(norm)
        # sigmoid binary CE against the true/noise labels
        p = mx.nd.sigmoid(logits)
        eps = 1e-7
        return -(labels * mx.nd.log(p + eps)
                 + (1 - labels) * mx.nd.log(1 - p + eps)).mean()


def train(n_pairs=32768, batch=256, epochs=4, lr=0.5, seed=0,
          verbose=True):
    centers, contexts, probs = synthetic_corpus(n_pairs, seed)
    rng = np.random.RandomState(seed + 1)
    noise_logp = np.log(K * probs + 1e-12).astype(np.float32)
    net = NCEWordVec(noise_logp)
    net.initialize(mx.init.Uniform(0.05))
    trainer = gluon.Trainer(net.collect_params(), "adagrad",
                            {"learning_rate": lr})
    labels = np.zeros((batch, 1 + K), np.float32)
    labels[:, 0] = 1.0
    labels_nd = mx.nd.array(labels)

    losses = []
    for ep in range(epochs):
        t0 = time.time()
        ep_loss, nb = 0.0, 0
        for s in range(0, n_pairs - batch + 1, batch):
            c = mx.nd.array(centers[s:s + batch], dtype="int32")
            # noise drawn from the unigram distribution (the NCE noise
            # model), true context in slot 0
            noise = rng.choice(VOCAB, size=(batch, K), p=probs)
            samp = np.concatenate(
                [contexts[s:s + batch, None], noise], axis=1)
            with autograd.record():
                loss = net(c, mx.nd.array(samp, dtype="int32"),
                           labels_nd)
            loss.backward()
            trainer.step(batch)
            ep_loss += float(loss)
            nb += 1
        losses.append(ep_loss / nb)
        if verbose:
            print("epoch %d nce-loss %.4f (%.1fs)"
                  % (ep, losses[-1], time.time() - t0))
    return net, losses


def pair_similarity(net, n_probe=200, seed=9):
    """Mean in·out score of planted pairs (i, i^1) vs random pairs —
    frequent words (low zipf ranks), where the corpus has coverage."""
    ein = net.in_embed.weight.data().asnumpy()
    eout = net.out_embed.weight.data().asnumpy()
    rng = np.random.RandomState(seed)
    ids = np.arange(n_probe)
    planted = (ein[ids] * eout[ids ^ 1]).sum(axis=1).mean()
    rand = (ein[ids] * eout[rng.permutation(ids)]).sum(axis=1).mean()
    return float(planted), float(rand)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    net, losses = train(epochs=args.epochs, verbose=not args.smoke)
    planted, rand = pair_similarity(net)
    print("nce loss %.4f -> %.4f; planted-pair cos %.3f vs random %.3f"
          % (losses[0], losses[-1], planted, rand))
    if args.smoke:
        assert losses[-1] < losses[0] * 0.9, losses
        assert planted > rand + 0.1, (planted, rand)
        print("OK")


if __name__ == "__main__":
    main()
