#!/usr/bin/env python
"""Low-level Module API walkthrough (reference:
``example/module/sequential_module.py`` + ``mnist_mlp.py``): drive
bind / init_params / init_optimizer / forward / backward / update by
hand instead of ``fit``, checkpoint with ``save_checkpoint``, and
resume with ``set_params`` — the under-the-hood loop every higher-level
trainer wraps.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def make_net(n_cls):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=n_cls, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    n, n_cls = 512, 4
    X = rng.uniform(0, 1, (n, 16)).astype(np.float32)
    Y = rng.randint(0, n_cls, (n,)).astype(np.float32)
    X[np.arange(n), Y.astype(int)] += 2.0

    it = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(make_net(n_cls), context=mx.cpu())

    # the manual loop fit() wraps
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        print("epoch %d %s" % (epoch, dict(metric.get_name_value())),
              flush=True)

    # checkpoint -> fresh module -> resume scoring
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "seqmod")
        mod.save_checkpoint(prefix, args.epochs)
        sym, arg, aux = mx.model.load_checkpoint(prefix, args.epochs)
        mod2 = mx.mod.Module(sym, context=mx.cpu())
        mod2.bind(data_shapes=it.provide_data,
                  label_shapes=it.provide_label, for_training=False)
        mod2.set_params(arg, aux)
        it.reset()
        metric2 = mx.metric.Accuracy()
        mod2.score(it, metric2)
        acc = dict(metric2.get_name_value())["accuracy"]
    print("restored accuracy: %.3f" % acc, flush=True)
    if acc < 0.9:
        raise SystemExit("manual module loop failed to converge")
    print("MODULE_OK", flush=True)


if __name__ == "__main__":
    main()
