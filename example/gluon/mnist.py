#!/usr/bin/env python
"""Gluon imperative training (reference: ``example/gluon/mnist.py``).

The modern-API counterpart of ``image-classification/train_mnist.py``:
HybridSequential net, ``autograd.record`` + ``Trainer.step`` loop,
``--hybridize`` compiles the whole net to one cached XLA callable.

Zero-egress: trains on a deterministic synthetic digit-like task by
default; pass ``--mnist-dir`` with idx files for the real dataset.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402


def synthetic_mnist(n, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n).astype(np.float32)
    palette = np.linspace(-1.0, 1.0, 10)
    X = rng.normal(0, 0.2, (n, 1, 28, 28)).astype(np.float32)
    X += palette[y.astype(int)][:, None, None, None]
    return X, y


def build_net(gluon):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(pool_size=2),
            gluon.nn.Conv2D(32, kernel_size=3, activation="relu"),
            gluon.nn.MaxPool2D(pool_size=2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    return net


def evaluate(net, loader, ctx, mx):
    correct = total = 0
    for X, y in loader:
        out = net(X.as_in_context(ctx))
        pred = out.asnumpy().argmax(axis=1)
        correct += int((pred == y.asnumpy()).sum())
        total += X.shape[0]
    return correct / total


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--num-examples", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--hybridize", action="store_true")
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--mnist-dir", default=None,
                    help="directory with MNIST idx files")
    ap.add_argument("--save", default=None, help="save params path")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    # DataLoader shuffling + init draw from the global RNGs
    np.random.seed(args.seed)
    mx.random.seed(args.seed)

    ctx = mx.cpu() if args.ctx == "cpu" else mx.tpu()
    if args.mnist_dir:
        it = mx.io.MNISTIter(
            image=os.path.join(args.mnist_dir, "train-images-idx3-ubyte"),
            label=os.path.join(args.mnist_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size)
        X = np.concatenate([b.data[0].asnumpy() for b in it])
        it.reset()
        y = np.concatenate([b.label[0].asnumpy() for b in it])
    else:
        X, y = synthetic_mnist(args.num_examples)
    n_train = int(0.9 * len(X))
    train_set = gluon.data.ArrayDataset(X[:n_train], y[:n_train])
    val_set = gluon.data.ArrayDataset(X[n_train:], y[n_train:])
    train_loader = gluon.data.DataLoader(train_set, args.batch_size,
                                         shuffle=True)
    val_loader = gluon.data.DataLoader(val_set, args.batch_size)

    net = build_net(gluon)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if args.hybridize:
        net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr,
                             "momentum": args.momentum})

    for epoch in range(args.num_epochs):
        loss_sum = 0.0
        nbatch = 0
        for Xb, yb in train_loader:
            Xb = Xb.as_in_context(ctx)
            yb = yb.as_in_context(ctx)
            with autograd.record():
                out = net(Xb)
                loss = loss_fn(out, yb)
            loss.backward()
            trainer.step(Xb.shape[0])
            loss_sum += float(loss.mean().asnumpy())
            nbatch += 1
        acc = evaluate(net, val_loader, ctx, mx)
        print("Epoch[%d] Train-loss=%.4f Validation-accuracy=%.4f"
              % (epoch, loss_sum / max(nbatch, 1), acc))

    if args.save:
        net.save_parameters(args.save)
        print("saved to %s" % args.save)


if __name__ == "__main__":
    main()
