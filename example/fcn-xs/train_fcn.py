#!/usr/bin/env python
"""Trainable FCN semantic segmentation (reference: ``example/fcn-xs`` —
fcn_xs.py/symbol_fcnxs.py, the FCN-8s/16s/32s family, scaled to a
zero-egress task).

The FCN recipe end to end: a downsampling conv backbone, a coarse
stride-8 score head, ``Deconvolution`` (transposed conv) learned
upsampling, and an FCN-16s-style SKIP FUSION — the stride-4 feature's
score map is added to the 2×-upsampled coarse scores before the final
upsample — trained with per-pixel softmax cross-entropy.  The smoke
asserts pixel accuracy and foreground mean-IoU rise well above the
random floor.

Scenes are colored rectangles on noise; the label is the per-pixel
class mask (0 = background, 1..C = color).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

S = 64
NUM_FG = 3          # foreground classes; +1 background
C = NUM_FG + 1


def synthetic_scene(rng, n, max_obj=3):
    imgs = rng.normal(0, 0.08, (n, 3, S, S)).astype(np.float32)
    masks = np.zeros((n, S, S), np.int64)
    for i in range(n):
        for _ in range(rng.randint(1, max_obj + 1)):
            cls = rng.randint(0, NUM_FG)
            w, h = rng.randint(12, 32, 2)
            x0 = rng.randint(0, S - w)
            y0 = rng.randint(0, S - h)
            imgs[i, cls, y0:y0 + h, x0:x0 + w] += 1.0
            masks[i, y0:y0 + h, x0:x0 + w] = cls + 1
    return imgs, masks


class FCN(gluon.nn.Block):
    """Backbone to stride 8, score heads at stride 4 and 8, learned
    deconv upsampling with skip fusion (FCN-16s pattern at 1/2 scale)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = gluon.nn.Conv2D(24, 3, strides=2, padding=1,
                                      activation="relu")   # stride 2
            self.c2 = gluon.nn.Conv2D(48, 3, strides=2, padding=1,
                                      activation="relu")   # stride 4
            self.c3 = gluon.nn.Conv2D(96, 3, strides=2, padding=1,
                                      activation="relu")   # stride 8
            self.c4 = gluon.nn.Conv2D(96, 3, padding=1,
                                      activation="relu")   # stride 8
            self.score8 = gluon.nn.Conv2D(C, 1)             # coarse
            self.score4 = gluon.nn.Conv2D(C, 1)             # skip
            # learned 2x upsamplers (reference: Deconvolution with
            # bilinear init; learned from scratch here)
            self.up2 = gluon.nn.Conv2DTranspose(C, 4, strides=2,
                                                padding=1)
            self.up4 = gluon.nn.Conv2DTranspose(C, 8, strides=4,
                                                padding=2)

    def forward(self, x):
        f2 = self.c1(x)
        f4 = self.c2(f2)
        f8 = self.c4(self.c3(f4))
        coarse = self.score8(f8)            # [B, C, S/8, S/8]
        up = self.up2(coarse)               # [B, C, S/4, S/4]
        fused = up + self.score4(f4)        # FCN skip fusion
        return self.up4(fused)              # [B, C, S, S]


def pixel_metrics(net, rng, n=16):
    imgs, masks = synthetic_scene(rng, n)
    logits = net(mx.nd.array(imgs)).asnumpy()
    pred = logits.argmax(1)
    acc = (pred == masks).mean()
    ious = []
    for c in range(1, C):
        inter = ((pred == c) & (masks == c)).sum()
        union = ((pred == c) | (masks == c)).sum()
        if union:
            ious.append(inter / union)
    return acc, float(np.mean(ious)) if ious else 0.0


def train(steps=250, batch=8, lr=0.003, seed=0, verbose=True):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    net = FCN()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for step in range(steps):
        imgs, masks = synthetic_scene(rng, batch)
        y = mx.nd.array(masks.reshape(batch, -1).astype(np.float32))
        with autograd.record():
            logits = net(mx.nd.array(imgs))
            flat = logits.reshape((batch, C, -1)).transpose((0, 2, 1))
            lp = mx.nd.log_softmax(flat, axis=-1)
            loss = -mx.nd.pick(lp, y, axis=2).mean()
        loss.backward()
        trainer.step(1)
        if verbose and (step + 1) % 50 == 0:
            print("step %d loss %.3f" % (step + 1,
                                         float(loss.asnumpy())))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=250)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    net = train(steps=args.steps, verbose=not args.smoke)
    acc, miou = pixel_metrics(net, np.random.RandomState(123))
    print("pixel accuracy %.3f  foreground mIoU %.3f" % (acc, miou))
    if args.smoke:
        # all-background predicts ~72% pixels but 0 IoU; random ~25%
        assert acc > 0.85 and miou > 0.4, (acc, miou)
        print("OK")


if __name__ == "__main__":
    main()
