#!/usr/bin/env python
"""CTC sequence recognition on synthetic speech (reference:
``example/speech_recognition/`` — DeepSpeech-style acoustic model
trained with CTC).

Zero-egress stand-in for LibriSpeech: each "utterance" is a sequence of
noisy per-phoneme spectral templates with random durations; the model
is a small BiLSTM over frames with a per-frame phoneme softmax trained
by CTC (alignment-free — the label sequence is shorter than the frame
sequence and durations vary).  The smoke test asserts the greedy-decode
label error rate collapses from ~1.0 to below 0.3.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

N_PHONES = 5        # alphabet (blank is index N_PHONES)
N_MEL = 12          # "spectrogram" bins
T_FRAMES = 48       # frames per utterance
L_MAX = 6           # max label length


def synthetic_utterances(n, seed=0):
    """Noisy per-phoneme templates with random durations."""
    rng = np.random.RandomState(seed)
    templates = rng.randn(N_PHONES, N_MEL) * 2.0
    X = np.zeros((n, T_FRAMES, N_MEL), np.float32)
    labels = np.full((n, L_MAX), -1.0, np.float32)  # -1 padded
    label_lens = np.zeros(n, np.int32)
    for i in range(n):
        L = rng.randint(2, L_MAX + 1)
        seq = rng.randint(0, N_PHONES, L)
        labels[i, :L] = seq
        label_lens[i] = L
        t = 0
        for ph in seq:
            dur = rng.randint(4, T_FRAMES // L_MAX + 3)
            end = min(t + dur, T_FRAMES)
            X[i, t:end] = templates[ph] + rng.normal(
                0, 0.4, (end - t, N_MEL))
            t = end
        # trailing silence stays zero + noise
        X[i, t:] += rng.normal(0, 0.4, (T_FRAMES - t, N_MEL))
    return X, labels, label_lens


class AcousticModel(gluon.nn.Block):
    def __init__(self, hidden=48, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.rnn = gluon.rnn.LSTM(hidden, bidirectional=True,
                                      layout="NTC")
            self.head = gluon.nn.Dense(N_PHONES + 1, flatten=False)

    def forward(self, x):
        return self.head(self.rnn(x))  # [N, T, phones+blank]


def greedy_decode(logits):
    """Collapse repeats, strip blanks (standard CTC decode)."""
    ids = logits.argmax(axis=-1).asnumpy().astype(int)
    out = []
    for row in ids:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != N_PHONES:
                seq.append(t)
            prev = t
        out.append(seq)
    return out


def label_error_rate(decoded, labels, label_lens):
    errs, total = 0, 0
    for d, lab, L in zip(decoded, labels, label_lens):
        ref = [int(v) for v in lab[:L]]
        # edit distance
        dp = np.arange(len(ref) + 1)
        for c in d:
            prev = dp.copy()
            dp[0] += 1
            for j in range(1, len(ref) + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (c != ref[j - 1]))
        errs += dp[-1]
        total += len(ref)
    return errs / max(total, 1)


def train(n_train=256, batch=32, epochs=30, lr=5e-3, seed=0,
          verbose=True):
    X, labels, label_lens = synthetic_utterances(n_train, seed)
    # seed the framework RNG too: parameter init draws from the global
    # stream, and an unlucky draw can leave CTC stuck near LER 1.0 for
    # several epochs — the smoke threshold needs a deterministic start
    mx.random.seed(seed)
    net = AcousticModel()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")

    lers = []
    for ep in range(epochs):
        ep_loss = 0.0
        for s in range(0, n_train, batch):
            x = mx.nd.array(X[s:s + batch])
            y = mx.nd.array(labels[s:s + batch])
            with autograd.record():
                logits = net(x)
                loss = ctc(logits, y).mean()
            loss.backward()
            trainer.step(x.shape[0])
            ep_loss += float(loss)
        decoded = greedy_decode(net(mx.nd.array(X[:64])))
        ler = label_error_rate(decoded, labels[:64], label_lens[:64])
        lers.append(ler)
        if verbose:
            print("epoch %d ctc-loss %.3f LER %.3f"
                  % (ep, ep_loss / (n_train // batch), ler))
    return net, lers


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    net, lers = train(epochs=args.epochs, verbose=not args.smoke)
    print("label error rate: %.3f -> %.3f" % (lers[0], lers[-1]))
    if args.smoke:
        assert lers[-1] < 0.3, lers
        print("OK")


if __name__ == "__main__":
    main()
