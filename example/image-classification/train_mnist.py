#!/usr/bin/env python
"""Train on MNIST-shaped data (reference:
``example/image-classification/train_mnist.py``).

The BASELINE "minimum end-to-end slice" config: MNISTIter-style data ->
Module.fit -> jit'd fwd/bwd -> SGD -> Accuracy -> checkpoint.  Runs on
real MNIST if ``--data-train`` points at a .rec, else a deterministic
synthetic MNIST-shaped task (zero-egress default).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(__file__))

from common import data, fit  # noqa: E402


def main():
    parser = argparse.ArgumentParser(
        description="train MNIST",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    # reference train_mnist.py defaults: mlp, sgd lr 0.05, 20 epochs
    parser.set_defaults(network="mlp", image_shape="1,28,28",
                        num_classes=10, num_examples=2048, batch_size=64,
                        num_epochs=20, lr=0.05)
    args = parser.parse_args()

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "symbols"))
    net_mod = __import__(args.network)
    sym = net_mod.get_symbol(num_classes=args.num_classes,
                             image_shape=args.image_shape)
    fit.fit(args, sym, data.get_iters)


if __name__ == "__main__":
    main()
