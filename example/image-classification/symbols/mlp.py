"""Multi-layer perceptron (reference: example/image-classification/
symbols/mlp.py)."""
import mxnet_tpu as mx


def get_symbol(num_classes=10, **kwargs):
    data = mx.sym.Variable("data")
    data = mx.sym.Flatten(data)
    fc1 = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    act1 = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act1, num_hidden=64, name="fc2")
    act2 = mx.sym.Activation(fc2, act_type="relu", name="relu2")
    fc3 = mx.sym.FullyConnected(act2, num_hidden=num_classes, name="fc3")
    return mx.sym.SoftmaxOutput(fc3, name="softmax")
