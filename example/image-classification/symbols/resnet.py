"""Symbolic ResNet (reference: example/image-classification/symbols/
resnet.py — CIFAR depths 3n*6+2 and ImageNet depths 18..152).

Kept symbolic (mx.sym) like the reference; the Gluon model zoo
(mxnet_tpu/gluon/model_zoo) is the imperative twin.
"""
import mxnet_tpu as mx


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottle_neck=True):
    bn1 = mx.sym.BatchNorm(data, fix_gamma=False, eps=2e-5, momentum=0.9,
                           name=name + "_bn1")
    act1 = mx.sym.Activation(bn1, act_type="relu", name=name + "_relu1")
    if bottle_neck:
        conv1 = mx.sym.Convolution(act1, num_filter=num_filter // 4,
                                   kernel=(1, 1), stride=(1, 1),
                                   pad=(0, 0), no_bias=True,
                                   name=name + "_conv1")
        bn2 = mx.sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5,
                               momentum=0.9, name=name + "_bn2")
        act2 = mx.sym.Activation(bn2, act_type="relu",
                                 name=name + "_relu2")
        conv2 = mx.sym.Convolution(act2, num_filter=num_filter // 4,
                                   kernel=(3, 3), stride=stride,
                                   pad=(1, 1), no_bias=True,
                                   name=name + "_conv2")
        bn3 = mx.sym.BatchNorm(conv2, fix_gamma=False, eps=2e-5,
                               momentum=0.9, name=name + "_bn3")
        act3 = mx.sym.Activation(bn3, act_type="relu",
                                 name=name + "_relu3")
        conv3 = mx.sym.Convolution(act3, num_filter=num_filter,
                                   kernel=(1, 1), stride=(1, 1),
                                   pad=(0, 0), no_bias=True,
                                   name=name + "_conv3")
        body = conv3
    else:
        conv1 = mx.sym.Convolution(act1, num_filter=num_filter,
                                   kernel=(3, 3), stride=stride,
                                   pad=(1, 1), no_bias=True,
                                   name=name + "_conv1")
        bn2 = mx.sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5,
                               momentum=0.9, name=name + "_bn2")
        act2 = mx.sym.Activation(bn2, act_type="relu",
                                 name=name + "_relu2")
        body = mx.sym.Convolution(act2, num_filter=num_filter,
                                  kernel=(3, 3), stride=(1, 1),
                                  pad=(1, 1), no_bias=True,
                                  name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(act1, num_filter=num_filter,
                                      kernel=(1, 1), stride=stride,
                                      no_bias=True, name=name + "_sc")
    return body + shortcut


def get_symbol(num_classes=10, num_layers=20, image_shape="3,32,32",
               **kwargs):
    image_shape = [int(x) for x in image_shape.split(",")] \
        if isinstance(image_shape, str) else list(image_shape)
    (nchannel, height, _) = image_shape
    if height <= 32:  # CIFAR
        assert (num_layers - 2) % 6 == 0
        per_stage = (num_layers - 2) // 6
        units = [per_stage] * 3
        filter_list = [16, 16, 32, 64]
        bottle_neck = False
    else:  # ImageNet
        cfg = {18: ([2, 2, 2, 2], False), 34: ([3, 4, 6, 3], False),
               50: ([3, 4, 6, 3], True), 101: ([3, 4, 23, 3], True),
               152: ([3, 8, 36, 3], True)}
        units, bottle_neck = cfg[num_layers]
        filter_list = [64, 256, 512, 1024, 2048] if bottle_neck \
            else [64, 64, 128, 256, 512]

    data = mx.sym.Variable("data")
    body = mx.sym.BatchNorm(data, fix_gamma=True, eps=2e-5,
                            momentum=0.9, name="bn_data")
    if height <= 32:
        body = mx.sym.Convolution(body, num_filter=filter_list[0],
                                  kernel=(3, 3), stride=(1, 1),
                                  pad=(1, 1), no_bias=True, name="conv0")
    else:
        body = mx.sym.Convolution(body, num_filter=filter_list[0],
                                  kernel=(7, 7), stride=(2, 2),
                                  pad=(3, 3), no_bias=True, name="conv0")
        body = mx.sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                                momentum=0.9, name="bn0")
        body = mx.sym.Activation(body, act_type="relu", name="relu0")
        body = mx.sym.Pooling(body, kernel=(3, 3), stride=(2, 2),
                              pad=(1, 1), pool_type="max")

    for i, n_units in enumerate(units):
        stride = (1, 1) if i == 0 else (2, 2)
        body = residual_unit(body, filter_list[i + 1], stride, False,
                             "stage%d_unit1" % (i + 1), bottle_neck)
        for j in range(n_units - 1):
            body = residual_unit(body, filter_list[i + 1], (1, 1), True,
                                 "stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck)

    bn1 = mx.sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=0.9,
                           name="bn1")
    relu1 = mx.sym.Activation(bn1, act_type="relu", name="relu1")
    pool1 = mx.sym.Pooling(relu1, global_pool=True, kernel=(7, 7),
                           pool_type="avg", name="pool1")
    flat = mx.sym.Flatten(pool1)
    fc1 = mx.sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(fc1, name="softmax")
