#!/usr/bin/env python
"""Inference throughput benchmark across the model zoo (reference:
``example/image-classification/benchmark_score.py`` — the script behind
docs/faq/perf.md's tables / BASELINE.md).

Per model x batch size it reports BOTH measurement disciplines (see
``mxnet_tpu.benchmark``): the compiled-loop device throughput (the
stable, gate-able number) and the per-dispatch user-path wall clock
(tunnel-sensitive; published with min/max spread).  Medians over
``--draws`` repetitions.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.benchmark import (  # noqa: E402
    compiled_throughput, percall_throughput)
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def score(model_name, batch_size, image_shape, dtype="float32",
          steps=30, draws=5, percall=False):
    net = getattr(vision, model_name)(classes=1000)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    data32 = mx.nd.array(np.random.uniform(
        size=(batch_size,) + image_shape).astype(np.float32))
    with mx.autograd.pause():
        # finish deferred init on a 1-sample input: the full-batch fp32
        # graph would be compiled once and thrown away after cast()
        net(data32[0:1])
    if dtype != "float32":
        net.cast(dtype)
        data = data32.astype(dtype)
    else:
        data = data32
    dev = compiled_throughput(net, data, steps=steps, draws=draws)
    res = {"compiled": dev}
    if percall:
        res["percall"] = percall_throughput(net, data, steps=steps,
                                            draws=draws)
    return res


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--networks", type=str,
                    default="alexnet,vgg16,inception_v3,resnet50_v1,"
                            "resnet152_v1")
    ap.add_argument("--batch-sizes", type=str, default="1,32,128,256")
    ap.add_argument("--image-shape", type=str, default="3,224,224")
    ap.add_argument("--dtype", type=str, default="bfloat16")
    ap.add_argument("--steps", type=int, default=30,
                    help="forwards per timed draw")
    ap.add_argument("--draws", type=int, default=5,
                    help="timed repetitions per cell (median reported)")
    ap.add_argument("--percall", action="store_true",
                    help="also time the per-dispatch user path")
    args = ap.parse_args()
    shape = tuple(int(x) for x in args.image_shape.split(","))
    for name in args.networks.split(","):
        for bs in (int(b) for b in args.batch_sizes.split(",")):
            # inception's 299x299 canonical input, like the reference
            s = (3, 299, 299) if "inception" in name and shape[1] == 224 \
                else shape
            r = score(name, bs, s, args.dtype, args.steps, args.draws,
                      args.percall)
            c = r["compiled"]
            line = ("network: %-14s batch: %4d dtype: %-9s  "
                    "compiled: %9.1f img/s [%9.1f, %9.1f]"
                    % (name, bs, args.dtype, c["median"], c["min"],
                       c["max"]))
            if "percall" in r:
                p = r["percall"]
                line += ("  percall: %9.1f img/s [%9.1f, %9.1f]"
                         % (p["median"], p["min"], p["max"]))
            print(line, flush=True)


if __name__ == "__main__":
    main()
