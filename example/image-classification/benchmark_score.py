#!/usr/bin/env python
"""Inference throughput benchmark across the model zoo (reference:
``example/image-classification/benchmark_score.py`` — the script behind
docs/faq/perf.md's tables / BASELINE.md)."""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def score(model_name, batch_size, image_shape, dtype="float32",
          warmup=3, iters=10):
    net = getattr(vision, model_name)(classes=1000)
    net.initialize(mx.init.Xavier())
    if dtype == "bfloat16":
        net.cast("bfloat16")
    net.hybridize()
    data = mx.nd.array(np.random.uniform(
        size=(batch_size,) + image_shape).astype(dtype if dtype != "bfloat16"
                                                 else "float32"))
    if dtype == "bfloat16":
        data = data.astype("bfloat16")
    for _ in range(warmup):
        net(data).wait_to_read()
    # queue all steps, sync once: per-call wait_to_read would measure
    # host<->device round-trip latency, not throughput (XLA dispatch is
    # async; the reference's engine is async for the same reason)
    tic = time.time()
    out = None
    for _ in range(iters):
        out = net(data)
    out.wait_to_read()
    dt = time.time() - tic
    return batch_size * iters / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--networks", type=str,
                    default="alexnet,resnet18_v1,resnet50_v1,vgg16,"
                            "mobilenet1_0,squeezenet1_0")
    ap.add_argument("--batch-sizes", type=str, default="1,32,128")
    ap.add_argument("--image-shape", type=str, default="3,224,224")
    ap.add_argument("--dtype", type=str, default="float32")
    args = ap.parse_args()
    shape = tuple(int(x) for x in args.image_shape.split(","))
    for name in args.networks.split(","):
        for bs in (int(b) for b in args.batch_sizes.split(",")):
            ips = score(name, bs, shape, args.dtype)
            print("network: %-16s batch: %4d  dtype: %-9s  %10.1f img/s"
                  % (name, bs, args.dtype, ips), flush=True)


if __name__ == "__main__":
    main()
