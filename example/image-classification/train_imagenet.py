#!/usr/bin/env python
"""ImageNet-shaped training driver (reference:
``example/image-classification/train_imagenet.py``).

The reference's baseline perf/accuracy script: ResNet over 3x224x224
records with the full augmentation pipeline (random crop + mirror +
mean subtraction through ImageRecordIter), stepped-lr multi-epoch
training, checkpoint-every-epoch, and resume via ``--load-epoch``.

Zero-egress default: ``--synthetic-rec`` builds a small JPEG RecordIO
set with the same shape (class-tinted photos, im2rec wire format), so
the WHOLE pipeline — record decode, augmenters, module fit, resume —
runs exactly as it would on real ImageNet .rec files; point
``--data-train`` at a real im2rec output to train for real.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(__file__))

import numpy as np  # noqa: E402

from common import data, fit  # noqa: E402


def make_synthetic_rec(path, n=128, classes=8, size=256, seed=0):
    """Class-tinted JPEGs in im2rec wire format (learnable, aug-friendly:
    the tint survives crops/flips)."""
    import cv2

    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    rng = np.random.RandomState(seed)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    idx_path = os.path.splitext(path)[0] + ".idx"  # im2rec convention
    rec = recordio.MXIndexedRecordIO(idx_path, path, "w")
    tints = rng.uniform(40, 215, (classes, 3))
    for i in range(n):
        cls = i % classes
        img = rng.normal(0, 18, (size, size, 3))
        img += tints[cls][None, None, :]
        ok, buf = cv2.imencode(".jpg",
                               np.clip(img, 0, 255).astype(np.uint8))
        assert ok
        header = recordio.IRHeader(0, float(cls), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.tobytes()))
    rec.close()
    return path


def main():
    parser = argparse.ArgumentParser(
        description="train on imagenet-shaped records",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    parser.add_argument("--synthetic-rec", type=str, default=None,
                        help="build a synthetic .rec at this path and "
                             "train on it (zero-egress default when no "
                             "--data-train)")
    parser.add_argument("--synthetic-examples", type=int, default=128)
    parser.add_argument("--num-layers", type=int, default=50)
    # reference train_imagenet defaults: resnet-50, 3x224x224, 1000
    # classes, stepped lr, full augmentation
    parser.set_defaults(network="resnet", num_layers=50,
                        image_shape="3,224,224", num_classes=1000,
                        num_examples=1281167, batch_size=32,
                        num_epochs=80, lr=0.1, lr_factor=0.1,
                        lr_step_epochs="30,60", rand_crop=True,
                        rand_mirror=True)
    args = parser.parse_args()

    if not args.data_train:
        path = args.synthetic_rec or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "synthetic_imagenet.rec")
        make_synthetic_rec(path, n=args.synthetic_examples,
                           classes=args.num_classes)
        args.data_train = path
        args.num_examples = args.synthetic_examples

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "symbols"))
    net_mod = __import__(args.network)
    sym = net_mod.get_symbol(num_classes=args.num_classes,
                             num_layers=args.num_layers,
                             image_shape=args.image_shape)
    fit.fit(args, sym, data.get_iters)


if __name__ == "__main__":
    main()
