#!/usr/bin/env python
"""Train a CIFAR-10-shaped task (reference:
``example/image-classification/train_cifar10.py`` — resnet by default)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(__file__))

from common import data, fit  # noqa: E402


def main():
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    parser.add_argument("--num-layers", type=int, default=20)
    parser.set_defaults(network="resnet", image_shape="3,32,32",
                        num_classes=10, num_examples=2048, batch_size=128,
                        num_epochs=3, lr=0.1, lr_step_epochs="60,100",
                        rand_crop=True, rand_mirror=True)
    args = parser.parse_args()

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "symbols"))
    net_mod = __import__(args.network)
    sym = net_mod.get_symbol(num_classes=args.num_classes,
                             num_layers=args.num_layers,
                             image_shape=args.image_shape)
    fit.fit(args, sym, data.get_iters)


if __name__ == "__main__":
    main()
