#!/usr/bin/env python
"""Score a saved checkpoint on a dataset (reference:
``example/image-classification/score.py``)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(__file__))

from common import data as data_mod  # noqa: E402


def main():
    import mxnet_tpu as mx

    ap = argparse.ArgumentParser()
    ap.add_argument("--model-prefix", type=str, required=True)
    ap.add_argument("--load-epoch", type=int, required=True)
    ap.add_argument("--batch-size", type=int, default=64)
    data_mod.add_data_args(ap)
    args = ap.parse_args()

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.model_prefix, args.load_epoch)
    _, val = data_mod.get_iters(args)
    mod = mx.mod.Module(sym, context=mx.cpu()
                        if not mx.context.num_tpus() else mx.tpu())
    mod.bind(data_shapes=val.provide_data,
             label_shapes=val.provide_label, for_training=False)
    mod.set_params(arg_params, aux_params)
    res = mod.score(val, ["accuracy"])
    for name, value in res:
        print("%s=%f" % (name, value))


if __name__ == "__main__":
    main()
