"""Data providers for the image-classification examples (reference:
``example/image-classification/common/data.py``).

Zero-egress environment: ``--synthetic`` (default) generates a
deterministic, learnable labeled image set; ``--data-train`` accepts a
RecordIO ``.rec`` produced by ``tools/im2rec.py`` for real data.
"""
import argparse

import numpy as np

import mxnet_tpu as mx


def add_data_args(parser):
    data = parser.add_argument_group("Data")
    data.add_argument("--data-train", type=str, default=None,
                      help="path to training .rec (im2rec)")
    data.add_argument("--data-val", type=str, default=None)
    data.add_argument("--image-shape", type=str, default="3,28,28")
    data.add_argument("--num-classes", type=int, default=10)
    data.add_argument("--num-examples", type=int, default=2048)
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939")
    data.add_argument("--rand-crop", action="store_true")
    data.add_argument("--rand-mirror", action="store_true")
    return parser


def _synthetic(args, kv_rank=0, kv_num=1, seed=0):
    """Deterministic learnable task: class-colored noisy images."""
    rng = np.random.RandomState(seed + kv_rank)
    shape = tuple(int(x) for x in args.image_shape.split(","))
    n = args.num_examples // kv_num
    y = rng.randint(0, args.num_classes, (n,)).astype(np.float32)
    # clear per-class mean shift + pixel noise: separable enough that a
    # few smoke-test epochs show real learning, still noisy per pixel
    palette = np.linspace(-1.0, 1.0, args.num_classes)
    X = rng.normal(0, 0.15, (n,) + shape).astype(np.float32)
    X += palette[y.astype(int)][:, None, None, None]
    return X, y


def get_iters(args, kv=None):
    """(train_iter, val_iter) — reference get_rec_iter shape."""
    rank = kv.rank if kv else 0
    num = kv.num_workers if kv else 1
    if args.data_train:
        shape = tuple(int(x) for x in args.image_shape.split(","))
        mean = [float(x) for x in args.rgb_mean.split(",")]
        train = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=shape,
            batch_size=args.batch_size, shuffle=True,
            rand_crop=args.rand_crop, rand_mirror=args.rand_mirror,
            mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
            part_index=rank, num_parts=num)
        val = None
        if args.data_val:
            val = mx.io.ImageRecordIter(
                path_imgrec=args.data_val, data_shape=shape,
                batch_size=args.batch_size,
                mean_r=mean[0], mean_g=mean[1], mean_b=mean[2])
        return train, val
    X, y = _synthetic(args, rank, num)
    if len(X) < 2 * args.batch_size:
        raise ValueError(
            "num-examples per worker (%d) must be at least 2x batch-size "
            "(%d) to leave both a train and a val split"
            % (len(X), args.batch_size))
    # val = 1/8th, but never so much that train drops below one batch
    n_val = min(max(len(X) // 8, args.batch_size),
                len(X) - args.batch_size)
    train = mx.io.NDArrayIter(X[n_val:], y[n_val:], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(X[:n_val], y[:n_val], args.batch_size)
    return train, val
