"""Shared training driver (reference:
``example/image-classification/common/fit.py:148`` — the fit() that every
train_* script calls: kvstore, optimizer, LR schedule, checkpoints,
Speedometer).
"""
import argparse
import logging
import os


def add_fit_args(parser):
    train = parser.add_argument_group("Training")
    train.add_argument("--network", type=str, default="mlp")
    train.add_argument("--batch-size", type=int, default=64)
    train.add_argument("--num-epochs", type=int, default=3)
    train.add_argument("--lr", type=float, default=0.05)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str, default="")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--kv-store", type=str, default="device")
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str, default=None)
    train.add_argument("--load-epoch", type=int, default=None)
    train.add_argument("--dtype", type=str, default="float32",
                       choices=["float32", "bfloat16"])
    train.add_argument("--ctx", type=str, default="auto",
                       choices=["auto", "tpu", "cpu"])
    return parser


def _context(args):
    import mxnet_tpu as mx

    if args.ctx == "cpu":
        return mx.cpu()
    if args.ctx == "tpu":
        return mx.tpu()
    return mx.tpu() if mx.context.num_tpus() else mx.cpu()


def fit(args, network, data_loader, **kwargs):
    """Train `network` (a Symbol) on the iterators from data_loader
    (reference fit.py:148)."""
    import mxnet_tpu as mx

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    kv = mx.kv.create(args.kv_store)
    train, val = data_loader(args, kv)

    lr_scheduler = None
    if args.lr_step_epochs:
        epoch_size = max(args.num_examples // args.batch_size //
                         max(kv.num_workers, 1), 1)
        steps = [epoch_size * int(e)
                 for e in args.lr_step_epochs.split(",") if e]
        if steps:
            lr_scheduler = mx.lr_scheduler.MultiFactorScheduler(
                step=steps, factor=args.lr_factor)

    optimizer_params = {"learning_rate": args.lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag", "signum"):
        optimizer_params["momentum"] = args.mom
    if lr_scheduler is not None:
        optimizer_params["lr_scheduler"] = lr_scheduler

    mod = mx.mod.Module(network, context=_context(args))
    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch

    checkpoint = None
    if args.model_prefix:
        os.makedirs(os.path.dirname(args.model_prefix) or ".",
                    exist_ok=True)
        checkpoint = mx.callback.do_checkpoint(args.model_prefix)

    mod.fit(train,
            eval_data=val,
            eval_metric=["accuracy"],
            kvstore=kv,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            arg_params=arg_params,
            aux_params=aux_params,
            begin_epoch=begin_epoch,
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches),
            epoch_end_callback=checkpoint,
            **kwargs)
    return mod
