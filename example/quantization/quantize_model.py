#!/usr/bin/env python
"""Post-training INT8 quantization example (reference:
``example/quantization/imagenet_gen_qsym.py``): train (or load) an fp32
model, calibrate, quantize, compare accuracies, save the int8 model."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.contrib.quantization import quantize_model

    ap = argparse.ArgumentParser()
    ap.add_argument("--model-prefix", type=str, default=None,
                    help="load an existing checkpoint instead of training")
    ap.add_argument("--load-epoch", type=int, default=0)
    ap.add_argument("--calib-mode", type=str, default="entropy",
                    choices=["none", "naive", "entropy"])
    ap.add_argument("--num-calib-examples", type=int, default=128)
    ap.add_argument("--out-prefix", type=str, default="model_int8")
    ap.add_argument("--image-shape", type=str, default="3,16,16",
                    help="input shape (must match a loaded checkpoint)")
    ap.add_argument("--num-classes", type=int, default=4)
    args = ap.parse_args()

    shape = tuple(int(x) for x in args.image_shape.split(","))
    rng = np.random.RandomState(0)
    X = rng.uniform(0, 1, (512,) + shape).astype(np.float32)
    Y = rng.randint(0, args.num_classes, (512,)).astype(np.float32)
    # unit per-class mean spacing: far above the noise floor, so the
    # demo net trains to high accuracy before quantization; inputs are
    # normalized like a real pipeline (unnormalized [0,5] data with the
    # default tiny-uniform init stalls at chance)
    X += Y[:, None, None, None]
    X = (X - X.mean()) / X.std()

    if args.model_prefix:
        sym, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
    else:
        data = mx.sym.Variable("data")
        net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                                 name="conv1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                             pool_type="max")
        net = mx.sym.Flatten(net)
        net = mx.sym.FullyConnected(net, num_hidden=args.num_classes,
                                    name="fc1")
        sym = mx.sym.SoftmaxOutput(net, name="softmax")
        it = mx.io.NDArrayIter(X, Y, 64, shuffle=True)
        mod = mx.mod.Module(sym)
        mod.fit(it, num_epoch=12, optimizer="adam",
                optimizer_params={"learning_rate": 5e-3},
                initializer=mx.init.Xavier())
        arg_params, aux_params = mod.get_params()

    calib = mx.io.NDArrayIter(X[:args.num_calib_examples],
                              Y[:args.num_calib_examples], 64)
    qsym, qargs, qauxs = quantize_model(
        sym, arg_params, aux_params, calib_mode=args.calib_mode,
        calib_data=calib, num_calib_examples=args.num_calib_examples)

    def acc(s, a, x):
        shapes = {"data": (64,) + shape, "softmax_label": (64,)}
        for n in s.list_arguments():
            if n in a:
                shapes[n] = tuple(a[n].shape)
        exe = s.simple_bind(grad_req="null", **shapes)
        exe.copy_params_from(a, x, allow_extra_params=True)
        hit = 0
        for i in range(0, len(X), 64):
            out = exe.forward(is_train=False, data=X[i:i + 64])[0]
            hit += (out.asnumpy().argmax(1) == Y[i:i + 64]).sum()
        return hit / len(X)

    print("fp32 accuracy: %.4f" % acc(sym, arg_params, aux_params))
    print("int8 accuracy: %.4f" % acc(qsym, qargs, qauxs))
    os.makedirs(os.path.dirname(args.out_prefix) or ".", exist_ok=True)
    mx.model.save_checkpoint(args.out_prefix, 0, qsym, qargs, qauxs)
    print("saved %s-symbol.json / %s-0000.params"
          % (args.out_prefix, args.out_prefix))


if __name__ == "__main__":
    main()
