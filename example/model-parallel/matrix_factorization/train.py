#!/usr/bin/env python
"""Model-parallel matrix factorization.

Reference: ``example/model-parallel/matrix_factorization/`` — the user
and item embedding tables live on *different* devices via ``group2ctx``
placement (``mx.AttrScope(ctx_group=...)`` -> ``Module(group2ctxs=...)``;
reference plumbing ``graph_executor.cc:909-915`` AssignContext +
auto-inserted cross-device copies).

TPU-native shape: each ctx_group pins its subgraph's variables to a
device with ``jax.device_put``; XLA inserts the transfers the reference
inserts as explicit copy nodes.  With one chip both groups land on the
same device and the script still runs (placement is a layout choice,
not a semantic one).  Synthetic MovieLens-like ratings, zero egress.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

import numpy as np  # noqa: E402


def synthetic_ratings(num_users, num_items, n, rank=8, seed=0):
    rng = np.random.RandomState(seed)
    u_lat = rng.randn(num_users, rank) / np.sqrt(rank)
    i_lat = rng.randn(num_items, rank) / np.sqrt(rank)
    users = rng.randint(0, num_users, n)
    items = rng.randint(0, num_items, n)
    scores = (u_lat[users] * i_lat[items]).sum(1) + 0.1 * rng.randn(n)
    return users.astype(np.float32), items.astype(np.float32), \
        scores.astype(np.float32)


def matrix_fact_net(factor_size, num_users, num_items):
    import mxnet_tpu as mx

    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score")
    # user tower on group "dev1", item tower on "dev2" (reference split)
    with mx.AttrScope(ctx_group="dev1"):
        user_w = mx.sym.Variable("user_weight")
        u = mx.sym.Embedding(user, weight=user_w, input_dim=num_users,
                             output_dim=factor_size, name="user_embed")
    with mx.AttrScope(ctx_group="dev2"):
        item_w = mx.sym.Variable("item_weight")
        i = mx.sym.Embedding(item, weight=item_w, input_dim=num_items,
                             output_dim=factor_size, name="item_embed")
    pred = mx.sym.sum(u * i, axis=1)
    return mx.sym.LinearRegressionOutput(pred, score, name="lro")


def main():
    import mxnet_tpu as mx

    ap = argparse.ArgumentParser()
    ap.add_argument("--num-users", type=int, default=500)
    ap.add_argument("--num-items", type=int, default=300)
    ap.add_argument("--num-samples", type=int, default=20000)
    ap.add_argument("--factor-size", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    args = ap.parse_args()

    users, items, scores = synthetic_ratings(
        args.num_users, args.num_items, args.num_samples)
    it = mx.io.NDArrayIter({"user": users, "item": items},
                           {"score": scores},
                           batch_size=args.batch_size, shuffle=True,
                           label_name="score")

    net = matrix_fact_net(args.factor_size, args.num_users, args.num_items)
    ctx = mx.cpu() if args.ctx == "cpu" else mx.tpu()
    # two device groups: on multi-device hosts they are distinct devices,
    # on one chip they alias (same placement degrade the reference allows)
    import jax

    devs = jax.local_devices()
    group2ctxs = {"dev1": mx.Context(ctx.device_type, 0),
                  "dev2": mx.Context(ctx.device_type,
                                     1 if len(devs) > 1 else 0)}
    mod = mx.mod.Module(net, data_names=("user", "item"),
                        label_names=("score",), context=ctx,
                        group2ctxs=group2ctxs)
    mod.fit(it, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": 0.9,
                              "rescale_grad": 1.0 / args.batch_size},
            eval_metric="mse",
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 20))
    it.reset()
    mse = mod.score(it, "mse")
    print("Final MSE=%.4f" % dict(mse)["mse"])


if __name__ == "__main__":
    main()
