#!/usr/bin/env python
"""Sort digit sequences with a bidirectional LSTM (reference:
``example/bi-lstm-sort/`` — the classic seq2seq-lite task proving
recurrent nets learn content-based permutation).

Input: a sequence of k digits; target: the same digits sorted.  The
model is a BiLSTM encoder with a per-position classifier (the reference
formulation: each output position classifies which digit belongs
there).  The smoke test asserts >90% per-position accuracy and that
whole sequences sort correctly most of the time.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

VOCAB = 10
SEQ = 6


def make_data(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, VOCAB, (n, SEQ)).astype(np.float32)
    y = np.sort(x, axis=1)
    return x, y


class SortNet(gluon.nn.Block):
    def __init__(self, hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = gluon.nn.Embedding(VOCAB, 32)
            self.rnn = gluon.rnn.LSTM(hidden, bidirectional=True,
                                      layout="NTC")
            self.head = gluon.nn.Dense(VOCAB, flatten=False)

    def forward(self, x):
        return self.head(self.rnn(self.embed(x)))  # [N, SEQ, VOCAB]


def train(n_train=2048, batch=64, epochs=12, lr=3e-3, seed=0,
          verbose=True):
    X, Y = make_data(n_train, seed)
    net = SortNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    accs = []
    for ep in range(epochs):
        for s in range(0, n_train, batch):
            xb = mx.nd.array(X[s:s + batch])
            yb = mx.nd.array(Y[s:s + batch])
            with autograd.record():
                logits = net(xb)
                loss = ce(logits.reshape((-1, VOCAB)),
                          yb.reshape((-1,))).mean()
            loss.backward()
            trainer.step(xb.shape[0])
        Xv, Yv = make_data(256, seed + 1)
        pred = net(mx.nd.array(Xv)).asnumpy().argmax(-1)
        accs.append(float((pred == Yv).mean()))
        if verbose:
            print("epoch %d per-position accuracy %.3f" % (ep, accs[-1]))
    full = float((pred == Yv).all(axis=1).mean())
    return net, accs, full


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    net, accs, full = train(epochs=args.epochs, verbose=not args.smoke)
    print("per-position accuracy %.3f -> %.3f; exact-sequence %.3f"
          % (accs[0], accs[-1], full))
    if args.smoke:
        assert accs[-1] > 0.9, accs
        assert full > 0.5, full
        print("OK")


if __name__ == "__main__":
    main()
