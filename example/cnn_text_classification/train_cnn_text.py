#!/usr/bin/env python
"""CNN text classification (reference:
``example/cnn_text_classification`` — Kim 2014's CNN-non-static on MR,
scaled to a zero-egress task).

The Kim-CNN architecture exactly: token embedding, PARALLEL convolution
branches with filter widths 3/4/5 over the embedded sequence,
max-over-time pooling per branch, concat, dropout, dense softmax.  The
synthetic corpus assigns each class a set of signature trigrams planted
in random token noise — precisely the pattern max-over-time conv
filters exist to detect.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

VOCAB = 200
SEQ = 32
NUM_CLASSES = 4
EMBED = 32
FILTERS = (3, 4, 5)
NUM_FILT = 32


def make_corpus(rng, n):
    """Each class owns 3 signature trigrams from a reserved token range;
    a sample is noise tokens with 1-2 planted signatures."""
    sigs = {}
    for c in range(NUM_CLASSES):
        base = 150 + c * 10
        sigs[c] = [(base + i, base + i + 1, base + i + 2)
                   for i in range(0, 9, 3)]
    X = rng.randint(0, 150, (n, SEQ))
    y = rng.randint(0, NUM_CLASSES, n)
    for i in range(n):
        for _ in range(rng.randint(1, 3)):
            tri = sigs[y[i]][rng.randint(3)]
            p = rng.randint(0, SEQ - 3)
            X[i, p:p + 3] = tri
    return X.astype(np.float32), y.astype(np.float32)


class KimCNN(gluon.nn.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = gluon.nn.Embedding(VOCAB, EMBED)
            self.convs = []
            for i, w in enumerate(FILTERS):
                conv = gluon.nn.Conv2D(NUM_FILT, (w, EMBED))
                setattr(self, "conv%d" % w, conv)
                self.convs.append(conv)
            self.drop = gluon.nn.Dropout(0.3)
            self.out = gluon.nn.Dense(NUM_CLASSES)

    def forward(self, tokens):
        # [B, T] -> [B, 1, T, E] "image" over the sequence
        e = self.embed(tokens).expand_dims(1)
        pooled = []
        for conv in self.convs:
            h = mx.nd.relu(conv(e))          # [B, F, T-w+1, 1]
            pooled.append(mx.nd.max(h, axis=(2, 3)))  # max-over-time
        return self.out(self.drop(mx.nd.concat(*pooled, dim=1)))


def accuracy(net, X, y):
    with autograd.pause():
        pred = net(mx.nd.array(X)).asnumpy().argmax(1)
    return (pred == y).mean()


def train(epochs=8, batch=32, lr=0.002, seed=0, verbose=True):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    Xtr, ytr = make_corpus(rng, 512)
    Xte, yte = make_corpus(rng, 256)
    net = KimCNN()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    for ep in range(epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for i in range(0, len(Xtr), batch):
            idx = perm[i:i + batch]
            xb = mx.nd.array(Xtr[idx])
            yb = mx.nd.array(ytr[idx])
            with autograd.record():
                loss = sce(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        if verbose:
            print("epoch %d loss %.3f test acc %.3f"
                  % (ep, tot / max(1, len(Xtr) // batch),
                     accuracy(net, Xte, yte)))
    return net, accuracy(net, Xte, yte)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    net, acc = train(epochs=args.epochs, verbose=not args.smoke)
    print("test accuracy: %.3f" % acc)
    if args.smoke:
        assert acc > 0.85, acc  # random = 0.25
        print("OK")


if __name__ == "__main__":
    main()
