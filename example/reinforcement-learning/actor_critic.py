#!/usr/bin/env python
"""Advantage actor-critic on CartPole (reference:
``example/reinforcement-learning/`` — a3c/parallel_actor_critic: policy
gradient with a learned value baseline).

Zero-egress: the CartPole dynamics are the classic 20-line numpy
physics (no gym).  One gluon net with policy + value heads, advantage =
n-step return minus baseline, entropy bonus; the smoke test asserts the
mean episode return clearly beats the random-policy floor.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402


class CartPole:
    """Classic cart-pole physics (Barto, Sutton & Anderson 1983)."""

    def __init__(self, seed=0):
        self.rng = np.random.RandomState(seed)
        self.g, self.mc, self.mp = 9.8, 1.0, 0.1
        self.l, self.fmag, self.dt = 0.5, 10.0, 0.02
        self.reset()

    def reset(self):
        self.s = self.rng.uniform(-0.05, 0.05, 4)
        return self.s.copy()

    def step(self, action):
        x, xd, th, thd = self.s
        f = self.fmag if action == 1 else -self.fmag
        ct, st = np.cos(th), np.sin(th)
        total = self.mc + self.mp
        tmp = (f + self.mp * self.l * thd * thd * st) / total
        thacc = (self.g * st - ct * tmp) / (
            self.l * (4.0 / 3.0 - self.mp * ct * ct / total))
        xacc = tmp - self.mp * self.l * thacc * ct / total
        self.s = np.array([x + self.dt * xd, xd + self.dt * xacc,
                           th + self.dt * thd, thd + self.dt * thacc])
        done = abs(self.s[0]) > 2.4 or abs(self.s[2]) > 12 * np.pi / 180
        return self.s.copy(), 1.0, done


class ACNet(gluon.nn.Block):
    def __init__(self, n_actions=2, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = gluon.nn.Dense(64, activation="relu")
            self.policy = gluon.nn.Dense(n_actions)
            self.value = gluon.nn.Dense(1)

    def forward(self, x):
        h = self.body(x)
        return self.policy(h), self.value(h)


def run_episode(env, net, rng, max_steps=200):
    obs, acts, rews = [], [], []
    s = env.reset()
    for _ in range(max_steps):
        logits, _ = net(mx.nd.array(s[None].astype(np.float32)))
        p = mx.nd.softmax(logits)[0].asnumpy()
        a = rng.choice(2, p=p / p.sum())
        obs.append(s)
        acts.append(a)
        s, r, done = env.step(a)
        rews.append(r)
        if done:
            break
    return np.array(obs, np.float32), np.array(acts), np.array(rews)


def train(episodes=150, gamma=0.99, lr=0.01, entropy_w=0.03, seed=0,
          verbose=True, net=None):
    env = CartPole(seed)
    rng = np.random.RandomState(seed + 1)
    if net is None:
        mx.random.seed(seed)  # parameter init must be reproducible too
        net = ACNet()
        net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    returns = []
    for ep in range(episodes):
        obs, acts, rews = run_episode(env, net, rng)
        # discounted returns
        G = np.zeros(len(rews), np.float32)
        run = 0.0
        for t in reversed(range(len(rews))):
            run = rews[t] + gamma * run
            G[t] = run
        with autograd.record():
            logits, values = net(mx.nd.array(obs))
            logp = mx.nd.log_softmax(logits, axis=-1)
            chosen = mx.nd.pick(logp, mx.nd.array(acts), axis=1)
            adv = mx.nd.array(G) - values[:, 0]
            # normalized advantages stabilize the gradient scale across
            # wildly different episode lengths
            a_det = adv.detach()
            m = a_det.mean()
            c = a_det - m
            a_norm = c / (mx.nd.sqrt((c ** 2).mean()) + 1e-5)
            policy_loss = -(chosen * a_norm).mean()
            value_loss = (adv ** 2).mean()
            entropy = -(mx.nd.softmax(logits) * logp).sum(axis=1).mean()
            loss = policy_loss + 0.5 * value_loss - entropy_w * entropy
        loss.backward()
        trainer.step(1)
        returns.append(float(rews.sum()))
        if verbose and (ep + 1) % 20 == 0:
            print("episode %d mean return (last 20): %.1f"
                  % (ep + 1, np.mean(returns[-20:])))
    return net, returns


def greedy_eval(net, n_episodes=10, seed=123, max_steps=200):
    """Deterministic (argmax) policy rollout — the robust smoke metric:
    training curves are chaotic run-to-run (XLA CPU rounding differs
    under load and RL amplifies any ulp), but a trained policy's greedy
    return clears the random-policy floor reliably."""
    env = CartPole(seed)
    totals = []
    for _ in range(n_episodes):
        s = env.reset()
        total = 0.0
        for _ in range(max_steps):
            logits, _ = net(mx.nd.array(s[None].astype(np.float32)))
            a = int(logits[0].asnumpy().argmax())
            s, r, done = env.step(a)
            total += r
            if done:
                break
        totals.append(total)
    return float(np.mean(totals))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=150)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    # Policy-gradient training occasionally collapses, and XLA CPU
    # compute is not bit-deterministic run-to-run, so a fixed seed does
    # NOT give a fixed outcome (measured: the same seed's greedy eval
    # ranged 11-200 over 10 runs).  The smoke protocol is therefore an
    # anytime one: each seed gets a CONTINUATION round of further
    # training if its first eval misses the bar (RL training is anytime
    # — a slow-but-learning policy clears on continuation), and up to
    # four seeds run before the smoke fails.  Flakiness measured with
    # tools/flakiness_checker.py; see tests/test_examples.py.
    bar = 45.0
    best = 0.0
    for seed in range(4):
        net = None
        for attempt in range(2):
            net, returns = train(episodes=args.episodes, seed=seed,
                                 verbose=not args.smoke, net=net)
            first = np.mean(returns[:20])
            last = np.mean(returns[-20:])
            score = greedy_eval(net)
            print("seed %d%s: mean return first-20 %.1f -> last-20 "
                  "%.1f; greedy eval %.1f"
                  % (seed, " (cont.)" if attempt else "", first, last,
                     score))
            best = max(best, score)
            if score > bar:
                break
        if best > bar:
            break
    if args.smoke:
        # random CartPole policies average ~20 steps greedily
        assert best > bar, best
        print("OK")


if __name__ == "__main__":
    main()
