#!/usr/bin/env python
"""Large-vocab sparse training: the table CANNOT fit in device memory.

Reference: ``example/sparse/`` + the kvstore row_sparse flow
(``src/kvstore/kvstore_dist.h:448-512``) — the reference's headline
sparse capability is training embeddings whose full table exceeds
accelerator memory, by pulling only the rows each batch touches.

Here a logistic regression over features hashed into a 500M-row table
(500M x 8 fp32 = 16 GB > the chip's HBM) trains with
``kv.init_host_rows`` + ``row_sparse_pull(row_ids=...)`` +
``push(row_ids=...)``: rows live host-side (lazily materialized), the
device only ever sees the gathered batch rows, and the kvstore's
transfer counters prove it.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

VOCAB = 500_000_000          # 500M rows x dim 8 fp32 = 16 GB: > HBM
DIM = 8
POOL = 4096                  # features that actually occur
NNZ = 32                     # active features per example


def make_dataset(n, seed=0):
    rng = np.random.RandomState(seed)
    # the occurring features live at arbitrary positions in the huge id
    # space — realistic for hashed categorical features
    pool_ids = rng.choice(VOCAB, size=POOL, replace=False).astype(np.int64)
    w_true = rng.randn(POOL).astype(np.float32)
    feats = rng.randint(0, POOL, size=(n, NNZ))
    logits = w_true[feats].sum(axis=1) / np.sqrt(NNZ)
    y = (logits > 0).astype(np.float32)
    return pool_ids[feats], y


def train(epochs=3, batch=64, n_train=1024, lr=30.0, verbose=True,
          kv_type="local"):
    ids, y = make_dataset(n_train)
    kv = mx.kv.create(kv_type)
    if kv.num_workers > 1:
        # data-parallel sharding; the row table is shared through the
        # host parameter server (server-side sparse reduce)
        ids = ids[kv.rank::kv.num_workers]
        y = y[kv.rank::kv.num_workers]
        n_train = len(y)
        verbose = verbose and kv.rank == 0
    kv.init_host_rows("emb", (VOCAB, DIM), "float32")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=lr))
    proj = mx.nd.array(np.ones((DIM, 1), np.float32) / DIM)

    losses = []
    for epoch in range(epochs):
        ep = 0.0
        nb = 0
        for s in range(0, n_train, batch):
            bi = ids[s:s + batch]                  # [b, NNZ] int64
            by = y[s:s + batch]
            uniq, inv = np.unique(bi, return_inverse=True)
            inv = inv.reshape(-1)  # numpy>=2 returns input-shaped inverse
            rows = kv.row_sparse_pull("emb", row_ids=uniq)  # [u, DIM]
            # score_i = mean_j mean_d emb[id_ij, d]
            emb = mx.nd.take(rows, mx.nd.array(
                inv.reshape(bi.shape).astype(np.int32)))    # [b,NNZ,DIM]
            score = mx.nd.dot(emb.sum(axis=1), proj)[:, 0]
            p = mx.nd.sigmoid(score)
            yb = mx.nd.array(by)
            eps = 1e-7
            loss = -(yb * mx.nd.log(p + eps)
                     + (1 - yb) * mx.nd.log(1 - p + eps)).mean()
            # closed-form grad wrt the gathered rows:
            # dL/demb[i,j,:] = (p_i - y_i) / (b * DIM)
            err = (p - yb).asnumpy() / (len(by) * DIM)
            grow = np.repeat(err[:, None], NNZ, axis=1).reshape(-1)
            grads = np.zeros((len(uniq), DIM), np.float32)
            np.add.at(grads, inv,
                      np.broadcast_to(grow[:, None],
                                      (grow.size, DIM)).copy())
            kv.push("emb", mx.nd.array(grads), row_ids=uniq)
            ep += float(loss)
            nb += 1
        losses.append(ep / nb)
        if verbose:
            stats = kv.host_row_stats("emb")
            print("epoch %d loss %.4f resident_rows %d transferred %d"
                  % (epoch, losses[-1], stats["resident_rows"],
                     stats["rows_transferred"]))
    return kv, losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--kv", default="local",
                    help="local or dist_sync (under tools/launch.py)")
    args = ap.parse_args()
    n_train = 512 if args.smoke else 1024
    kv, losses = train(epochs=args.epochs, n_train=n_train,
                       verbose=not args.smoke, kv_type=args.kv)
    stats = kv.host_row_stats("emb")
    table_gb = VOCAB * DIM * 4 / 1e9
    print("table %.0f GB logical; resident rows %d (%.6f%%); "
          "rows transferred %d; loss %.4f -> %.4f"
          % (table_gb, stats["resident_rows"],
             100.0 * stats["resident_rows"] / VOCAB,
             stats["rows_transferred"], losses[0], losses[-1]))
    if args.smoke:
        assert losses[-1] < losses[0] * 0.7, losses
        # the proof: the table could never fit on the device, yet only
        # the touched rows ever existed or moved
        assert table_gb > 15.0
        if kv.num_workers > 1:
            # resident rows live on the host parameter server; each
            # worker only observes its own transfer counters
            assert stats["rows_transferred"] > 0
        else:
            assert stats["resident_rows"] <= POOL
            assert stats["rows_transferred"] \
                <= args.epochs * (n_train // 64 + 1) * 64 * NNZ
        print("OK rank=%d" % kv.rank)


if __name__ == "__main__":
    main()
