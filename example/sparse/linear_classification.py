#!/usr/bin/env python
"""Sparse linear (logistic-regression) classification.

Reference: ``example/sparse/linear_classification/`` — trains a linear
model on LibSVM data with CSR batches and row-sparse lazy weight
updates so only the feature rows present in a batch are touched.

TPU-native shape: the CSR batch is dense-backed, so ``sparse.dot``
rides the MXU; the gradient is wrapped as a RowSparseNDArray carrying
the batch's active-feature indices, which routes the optimizer through
the lazy row-sparse update kernels (only those rows change — verified
bit-exactly by tests/test_sparse.py).

With no ``--data`` file a synthetic sparse dataset is generated
(zero-egress environment): y = sign(w_true . x) on 5%%-dense inputs.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402


def synthetic_libsvm(path, n=2000, nfeat=1000, density=0.05, seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(nfeat)
    with open(path, "w") as f:
        for _ in range(n):
            nnz = max(1, rng.binomial(nfeat, density))
            idx = np.sort(rng.choice(nfeat, nnz, replace=False))
            val = rng.randn(nnz)
            y = int(np.dot(w_true[idx], val) > 0)
            f.write("%d " % y +
                    " ".join("%d:%.5f" % (i, v)
                             for i, v in zip(idx, val)) + "\n")


def main():
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import sparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="LibSVM file")
    ap.add_argument("--num-features", type=int, default=1000)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--optimizer", default="sgd",
                    choices=["sgd", "adam"])
    args = ap.parse_args()

    path = args.data
    if path is None:
        path = os.path.join(tempfile.gettempdir(),
                            "sparse_linear_synth.libsvm")
        synthetic_libsvm(path, nfeat=args.num_features)

    it = mx.io.LibSVMIter(data_libsvm=path,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size, round_batch=False)

    w = mx.nd.zeros((args.num_features, 1))
    b = mx.nd.zeros((1,))
    opt = mx.optimizer.create(args.optimizer, learning_rate=args.lr,
                              lazy_update=True)
    updater = mx.optimizer.get_updater(opt)

    for epoch in range(args.num_epochs):
        it.reset()
        total, correct, loss_sum = 0, 0, 0.0
        for batch in it:
            X = batch.data[0]            # CSRNDArray (batch, nfeat)
            y = batch.label[0].reshape((-1, 1))
            logits = sparse.dot(X, w) + b
            p = 1.0 / (1.0 + mx.nd.exp(-logits))
            eps = 1e-7
            loss_sum += float(
                -(y * mx.nd.log(p + eps) +
                  (1 - y) * mx.nd.log(1 - p + eps)).mean().asnumpy())
            err = (p - y) / X.shape[0]
            gw_dense = sparse.dot(X, err, transpose_a=True)
            # active feature rows of this batch -> lazy row-sparse update
            active = np.nonzero(
                np.abs(X.asnumpy()).sum(axis=0) > 0)[0].astype(np.int64)
            gw = sparse.RowSparseNDArray(gw_dense.data, indices=active)
            updater(0, gw, w)
            updater(1, err.sum(axis=0), b)
            pred = (p.asnumpy() > 0.5).astype(np.float32)
            correct += int((pred == y.asnumpy()).sum())
            total += X.shape[0]
        print("Epoch[%d] Train-accuracy=%.4f Train-loss=%.4f"
              % (epoch, correct / total, loss_sum * args.batch_size / total))


if __name__ == "__main__":
    main()
