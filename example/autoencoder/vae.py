#!/usr/bin/env python
"""Variational autoencoder on MNIST-shaped data (reference:
``example/autoencoder/`` + the VAE tutorial family — unsupervised
representation learning with the reparameterization trick).

Zero-egress: class-structured synthetic digits (per-class blob patterns
+ noise).  Encoder outputs (mu, logvar); z = mu + eps*sigma backprops
through the sampling; loss = reconstruction BCE + KL(q||N(0,1)).  The
smoke test asserts (a) the ELBO improves substantially, (b) decoding
the class-mean latents reconstructs images closer to their own class
mean than to other classes' (the latent space is organized).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

D = 16 * 16
LATENT = 8
CLASSES = 4


def synthetic_digits(n, seed=0):
    rng = np.random.RandomState(seed)
    protos = (rng.rand(CLASSES, D) > 0.7).astype(np.float32)
    y = rng.randint(0, CLASSES, n)
    X = protos[y]
    flip = rng.rand(n, D) < 0.05
    X = np.where(flip, 1.0 - X, X).astype(np.float32)
    return X, y, protos


class VAE(gluon.nn.Block):
    def __init__(self, hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.enc = gluon.nn.Dense(hidden, activation="relu")
            self.mu = gluon.nn.Dense(LATENT)
            self.logvar = gluon.nn.Dense(LATENT)
            self.dec1 = gluon.nn.Dense(hidden, activation="relu")
            self.dec2 = gluon.nn.Dense(D)

    def encode(self, x):
        h = self.enc(x)
        return self.mu(h), self.logvar(h)

    def decode(self, z):
        return self.dec2(self.dec1(z))  # logits

    def forward(self, x, eps):
        mu, logvar = self.encode(x)
        z = mu + eps * mx.nd.exp(0.5 * logvar)  # reparameterization
        return self.decode(z), mu, logvar


def elbo_loss(logits, x, mu, logvar):
    p = mx.nd.sigmoid(logits)
    e = 1e-6
    bce = -(x * mx.nd.log(p + e)
            + (1 - x) * mx.nd.log(1 - p + e)).sum(axis=1)
    kl = -0.5 * (1 + logvar - mu ** 2 - mx.nd.exp(logvar)).sum(axis=1)
    return (bce + kl).mean()


def train(n_train=512, batch=64, epochs=20, lr=2e-3, seed=0,
          verbose=True):
    X, y, protos = synthetic_digits(n_train, seed)
    rng = np.random.RandomState(seed + 1)
    net = VAE()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    losses = []
    for ep in range(epochs):
        ep_loss, nb = 0.0, 0
        for s in range(0, n_train, batch):
            x = mx.nd.array(X[s:s + batch])
            eps = mx.nd.array(
                rng.randn(x.shape[0], LATENT).astype(np.float32))
            with autograd.record():
                logits, mu, logvar = net(x, eps)
                loss = elbo_loss(logits, x, mu, logvar)
            loss.backward()
            trainer.step(x.shape[0])
            ep_loss += float(loss)
            nb += 1
        losses.append(ep_loss / nb)
        if verbose and ep % 5 == 0:
            print("epoch %d -ELBO %.2f" % (ep, losses[-1]))
    return net, losses, (X, y, protos)


def latent_organization(net, data):
    """Decode class-mean latents; reconstruction should match own class
    prototype better than other classes'."""
    X, y, protos = data
    mu, _ = net.encode(mx.nd.array(X))
    mu = mu.asnumpy()
    hits = 0
    for c in range(CLASSES):
        zc = mu[y == c].mean(axis=0)
        rec = mx.nd.sigmoid(net.decode(
            mx.nd.array(zc[None].astype(np.float32)))).asnumpy()[0]
        dists = [np.abs(rec - protos[k]).mean() for k in range(CLASSES)]
        hits += int(np.argmin(dists) == c)
    return hits / CLASSES


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    net, losses, data = train(epochs=args.epochs,
                              verbose=not args.smoke)
    org = latent_organization(net, data)
    print("-ELBO %.2f -> %.2f; class-mean latent accuracy %.2f"
          % (losses[0], losses[-1], org))
    if args.smoke:
        assert losses[-1] < losses[0] * 0.5, losses
        assert org >= 0.75, org
        print("OK")


if __name__ == "__main__":
    main()
