#!/usr/bin/env python
"""Fast Gradient Sign Method adversarial examples (reference:
``example/adversary/adversary_generation.ipynb``): train a small
classifier, then perturb inputs along the sign of the input gradient
and measure the accuracy drop.

Demonstrates gradients with respect to INPUTS through the autograd
tape (``x.attach_grad()`` + ``autograd.record``), the piece the
training loop never touches.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402


def make_data(rng, n, side=8, n_cls=4):
    X = rng.uniform(0, 1, (n, 1, side, side)).astype(np.float32)
    Y = rng.randint(0, n_cls, (n,)).astype(np.float32)
    X += 0.8 * Y[:, None, None, None] / n_cls  # separable mean shift
    return X, Y


def accuracy(net, X, Y, batch=64):
    correct = 0
    for i in range(0, len(X), batch):
        out = net(mx.nd.array(X[i:i + batch]))
        correct += int((out.asnumpy().argmax(1) ==
                        Y[i:i + batch]).sum())
    return correct / len(X)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epsilon", type=float, default=0.15,
                    help="L-inf perturbation budget")
    ap.add_argument("--epochs", type=int, default=8)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    Xtr, Ytr = make_data(rng, 512)
    Xte, Yte = make_data(rng, 256)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})

    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        total = 0.0
        for i in range(0, len(Xtr), 64):
            sel = perm[i:i + 64]
            x = mx.nd.array(Xtr[sel])
            y = mx.nd.array(Ytr[sel])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(len(sel))
            total += float(loss.mean().asnumpy())
        print("epoch %d loss %.4f" % (epoch, total / (len(Xtr) // 64)),
              flush=True)

    clean_acc = accuracy(net, Xte, Yte)

    # FGSM: x_adv = x + eps * sign(dL/dx)
    adv = []
    for i in range(0, len(Xte), 64):
        x = mx.nd.array(Xte[i:i + 64])
        y = mx.nd.array(Yte[i:i + 64])
        x.attach_grad()
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        x_adv = x + args.epsilon * mx.nd.sign(x.grad)
        adv.append(mx.nd.clip(x_adv, 0, 2).asnumpy())
    Xadv = np.concatenate(adv, axis=0)
    adv_acc = accuracy(net, Xadv, Yte)

    print("clean accuracy: %.3f" % clean_acc, flush=True)
    print("adversarial accuracy (eps=%.2f): %.3f"
          % (args.epsilon, adv_acc), flush=True)
    if adv_acc >= clean_acc:
        raise SystemExit("FGSM failed to reduce accuracy")
    print("FGSM_OK", flush=True)


if __name__ == "__main__":
    main()
