#!/usr/bin/env python
"""DCGAN (reference: ``example/gan/dcgan.py`` — adversarial
generator/discriminator pair over conv/deconv stacks).

Gluon imperative flavor: two networks, alternating updates, the
generator driven by ``Deconvolution`` (checked against torch's
conv_transpose2d in tests/test_torch_oracle.py).  Trains on a
deterministic synthetic image distribution (class-conditional gaussian
blobs), zero egress; prints per-epoch D/G losses and the distribution
distance between generated and real pixel statistics.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402


def real_batch(rng, n, size=16):
    """Blobby images: a bright gaussian bump at a random position."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    cx = rng.uniform(4, size - 4, (n, 1, 1))
    cy = rng.uniform(4, size - 4, (n, 1, 1))
    img = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2) / 8.0)
    return (img[:, None] * 2.0 - 1.0).astype(np.float32)  # [-1, 1]


def build_nets(gluon, ngf=16, ndf=16, nz=32):
    G = gluon.nn.HybridSequential()
    with_bn = dict(use_bias=False)
    G.add(
        gluon.nn.Conv2DTranspose(ngf * 2, 4, strides=1, padding=0,
                                 **with_bn),  # 1x1 -> 4x4
        gluon.nn.BatchNorm(), gluon.nn.Activation("relu"),
        gluon.nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                 **with_bn),  # 4x4 -> 8x8
        gluon.nn.BatchNorm(), gluon.nn.Activation("relu"),
        gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                 use_bias=True),  # 8x8 -> 16x16
    )
    D = gluon.nn.HybridSequential()
    D.add(
        gluon.nn.Conv2D(ndf, 4, strides=2, padding=1),      # 16 -> 8
        gluon.nn.LeakyReLU(0.2),
        gluon.nn.Conv2D(ndf * 2, 4, strides=2, padding=1),  # 8 -> 4
        gluon.nn.LeakyReLU(0.2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(1),
    )
    return G, D, nz


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--steps-per-epoch", type=int, default=30)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    ctx = mx.cpu() if args.ctx == "cpu" else mx.tpu()
    G, D, nz = build_nets(gluon)
    G.initialize(mx.init.Normal(0.02), ctx=ctx)
    D.initialize(mx.init.Normal(0.02), ctx=ctx)

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    gt = gluon.Trainer(G.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    dt = gluon.Trainer(D.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})

    ones = mx.nd.ones((args.batch_size,), ctx=ctx)
    zeros = mx.nd.zeros((args.batch_size,), ctx=ctx)

    for epoch in range(args.num_epochs):
        dl_sum = gl_sum = 0.0
        for _ in range(args.steps_per_epoch):
            real = mx.nd.array(real_batch(rng, args.batch_size), ctx=ctx)
            z = mx.nd.random.normal(
                shape=(args.batch_size, nz, 1, 1), ctx=ctx)
            # --- D step: max log D(x) + log(1 - D(G(z)))
            with autograd.record():
                fake = G(z)
                out_real = D(real).reshape((-1,))
                out_fake = D(fake.detach()).reshape((-1,))
                d_loss = (loss_fn(out_real, ones) +
                          loss_fn(out_fake, zeros)).mean()
            d_loss.backward()
            dt.step(1)
            # --- G step: max log D(G(z))
            with autograd.record():
                fake = G(z)
                out = D(fake).reshape((-1,))
                g_loss = loss_fn(out, ones).mean()
            g_loss.backward()
            gt.step(1)
            dl_sum += float(d_loss.asnumpy())
            gl_sum += float(g_loss.asnumpy())

        # distribution distance: generated pixel stats vs real
        z = mx.nd.random.normal(shape=(256, nz, 1, 1), ctx=ctx)
        gen = G(z).asnumpy()
        ref = real_batch(rng, 256)
        dist = abs(gen.mean() - ref.mean()) + abs(gen.std() - ref.std())
        print("Epoch[%d] D-loss=%.4f G-loss=%.4f stat-dist=%.4f"
              % (epoch, dl_sum / args.steps_per_epoch,
                 gl_sum / args.steps_per_epoch, dist), flush=True)
    assert np.isfinite(dl_sum) and np.isfinite(gl_sum)
    print("final stat-dist %.4f" % dist)


if __name__ == "__main__":
    main()
