#!/usr/bin/env python
"""Trainable Faster R-CNN on synthetic scenes (reference:
``example/rcnn/train.py`` + ``symnet/`` scaled to a zero-egress task).

The full two-stage detection pipeline, end to end:

* a small conv backbone producing a stride-8 feature map,
* an RPN head (objectness + box deltas per anchor) trained with
  IoU-matched anchor targets (softmax CE + smooth-L1),
* ``Proposal`` (anchor decode + NMS, ``ops/detection.py``) turning RPN
  scores into ROIs,
* ``ROIPooling`` over the SHARED feature map — gradients from the
  second stage flow through the pooled features into the backbone,
  which is the architectural point of Faster R-CNN,
* an RCNN head (per-ROI class softmax + box refinement) trained with
  IoU-matched ROI targets,
* greedy decoding + a recall-style detection metric that must rise.

Scenes are colored rectangles on noise (class = color), as in the SSD
example — the same data regime, solved by the other detector family.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

S = 64                 # image size
STRIDE = 8             # backbone stride -> 8x8 feature map
NUM_CLASSES = 3        # foreground classes (colors); +1 background
SCALES = (2.0, 3.5, 5.0)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
POST_NMS = 16          # ROIs per image out of Proposal
POOL = (4, 4)


def synthetic_scene(rng, n, max_obj=2):
    imgs = rng.normal(0, 0.08, (n, 3, S, S)).astype(np.float32)
    labels = np.full((n, max_obj, 5), -1.0, np.float32)
    for i in range(n):
        for j in range(rng.randint(1, max_obj + 1)):
            cls = rng.randint(0, NUM_CLASSES)
            w, h = rng.randint(16, 36, 2)
            x0 = rng.randint(0, S - w)
            y0 = rng.randint(0, S - h)
            imgs[i, cls, y0:y0 + h, x0:x0 + w] += 1.0
            labels[i, j] = (cls, x0, y0, x0 + w - 1, y0 + h - 1)
    return imgs, labels


def anchor_grid():
    """[H*W*A, 4] anchors matching the Proposal op's layout
    (ratio-major then scale, centers on the stride grid)."""
    base = []
    px = py = (STRIDE - 1.0) * 0.5
    for r in RATIOS:
        size = STRIDE * STRIDE / r
        ws = round(np.sqrt(size))
        hs = round(ws * r)
        for s in SCALES:
            w, h = ws * s, hs * s
            base.append([px - 0.5 * (w - 1), py - 0.5 * (h - 1),
                         px + 0.5 * (w - 1), py + 0.5 * (h - 1)])
    base = np.asarray(base, np.float32)
    F = S // STRIDE
    shifts = np.arange(F, dtype=np.float32) * STRIDE
    sy, sx = np.meshgrid(shifts, shifts, indexing="ij")
    grid = np.stack([sx, sy, sx, sy], axis=-1)       # [F, F, 4]
    return (grid[:, :, None, :] + base[None, None]).reshape(-1, 4)


def iou_matrix(a, b):
    """[Na, Nb] IoU of corner boxes."""
    ax1, ay1, ax2, ay2 = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    iw = np.maximum(0, np.minimum(ax2, bx2) - np.maximum(ax1, bx1) + 1)
    ih = np.maximum(0, np.minimum(ay2, by2) - np.maximum(ay1, by1) + 1)
    inter = iw * ih
    area_a = (ax2 - ax1 + 1) * (ay2 - ay1 + 1)
    area_b = (bx2 - bx1 + 1) * (by2 - by1 + 1)
    return inter / np.maximum(area_a + area_b - inter, 1e-6)


def bbox_deltas(src, dst):
    """center/log-size regression targets from src boxes to dst boxes."""
    sw = src[:, 2] - src[:, 0] + 1.0
    sh = src[:, 3] - src[:, 1] + 1.0
    sx = src[:, 0] + 0.5 * (sw - 1)
    sy = src[:, 1] + 0.5 * (sh - 1)
    dw = dst[:, 2] - dst[:, 0] + 1.0
    dh = dst[:, 3] - dst[:, 1] + 1.0
    dx = dst[:, 0] + 0.5 * (dw - 1)
    dy = dst[:, 1] + 0.5 * (dh - 1)
    return np.stack([(dx - sx) / sw, (dy - sy) / sh,
                     np.log(dw / sw), np.log(dh / sh)], axis=1)


def apply_deltas(boxes, d):
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1) + d[:, 0] * w
    cy = boxes[:, 1] + 0.5 * (h - 1) + d[:, 1] * h
    nw = w * np.exp(np.clip(d[:, 2], -4, 4))
    nh = h * np.exp(np.clip(d[:, 3], -4, 4))
    out = np.stack([cx - 0.5 * (nw - 1), cy - 0.5 * (nh - 1),
                    cx + 0.5 * (nw - 1), cy + 0.5 * (nh - 1)], axis=1)
    return np.clip(out, 0, S - 1)


class FasterRCNN(gluon.nn.Block):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = gluon.nn.Conv2D(32, 3, strides=2, padding=1,
                                      activation="relu")
            self.c2 = gluon.nn.Conv2D(64, 3, strides=2, padding=1,
                                      activation="relu")
            self.c3 = gluon.nn.Conv2D(64, 3, strides=2, padding=1,
                                      activation="relu")
            self.rpn_conv = gluon.nn.Conv2D(64, 3, padding=1,
                                            activation="relu")
            self.rpn_cls = gluon.nn.Conv2D(2 * A, 1)
            self.rpn_bbox = gluon.nn.Conv2D(4 * A, 1)
            self.fc = gluon.nn.Dense(128, activation="relu")
            self.cls = gluon.nn.Dense(NUM_CLASSES + 1)
            self.bbox = gluon.nn.Dense(4)

    def features(self, x):
        return self.c3(self.c2(self.c1(x)))

    def rpn(self, feat):
        h = self.rpn_conv(feat)
        return self.rpn_cls(h), self.rpn_bbox(h)

    def head(self, feat, rois):
        pooled = mx.nd.ROIPooling(feat, rois, pooled_size=POOL,
                                  spatial_scale=1.0 / STRIDE)
        h = self.fc(pooled)
        return self.cls(h), self.bbox(h)


def rpn_targets(anchors, labels_np):
    """Per image: (cls_target [N] in {-1,0,1}, bbox_target [N,4])."""
    N = anchors.shape[0]
    cls_t = np.full(N, -1.0, np.float32)  # -1 = ignore
    box_t = np.zeros((N, 4), np.float32)
    gts = labels_np[labels_np[:, 0] >= 0]
    if len(gts) == 0:
        cls_t[:] = 0
        return cls_t, box_t
    iou = iou_matrix(anchors, gts[:, 1:5])
    best_gt = iou.argmax(axis=1)
    best_iou = iou.max(axis=1)
    cls_t[best_iou < 0.3] = 0
    cls_t[best_iou >= 0.5] = 1
    # each gt's best anchor is always positive (classic fallback)
    cls_t[iou.argmax(axis=0)] = 1
    pos = np.where(cls_t == 1)[0]
    box_t[pos] = bbox_deltas(anchors[pos], gts[best_gt[pos], 1:5])
    # subsample negatives to balance
    neg = np.where(cls_t == 0)[0]
    if len(neg) > 3 * max(len(pos), 4):
        drop = np.random.RandomState(0).choice(
            neg, len(neg) - 3 * max(len(pos), 4), replace=False)
        cls_t[drop] = -1
    return cls_t, box_t


def rcnn_targets(rois_np, labels_np):
    """Per ROI: class target (0 = bg) + refine deltas for foreground."""
    R = rois_np.shape[0]
    cls_t = np.zeros(R, np.float32)
    box_t = np.zeros((R, 4), np.float32)
    mask = np.zeros(R, np.float32)
    for i in range(R):
        b = int(rois_np[i, 0])
        gts = labels_np[b]
        gts = gts[gts[:, 0] >= 0]
        if len(gts) == 0:
            continue
        iou = iou_matrix(rois_np[i:i + 1, 1:5], gts[:, 1:5])[0]
        j = iou.argmax()
        if iou[j] >= 0.5:
            cls_t[i] = gts[j, 0] + 1
            box_t[i] = bbox_deltas(rois_np[i:i + 1, 1:5],
                                   gts[j:j + 1, 1:5])[0]
            mask[i] = 1
    return cls_t, box_t, mask


def detect(net, imgs_np, score_thresh=0.25):
    """Greedy decode: top class per ROI + box refinement."""
    x = mx.nd.array(imgs_np)
    feat = net.features(x)
    rpn_c, rpn_b = net.rpn(feat)
    B = imgs_np.shape[0]
    cp = mx.nd.softmax(rpn_c.reshape((B, 2, -1)), axis=1)
    cp = cp.reshape((B, 2 * A, S // STRIDE, S // STRIDE))
    im_info = mx.nd.array(np.tile([S, S, 1.0], (B, 1)).astype(np.float32))
    rois = mx.nd.Proposal(cp, rpn_b, im_info, feature_stride=STRIDE,
                          scales=SCALES, ratios=RATIOS,
                          rpn_pre_nms_top_n=64, rpn_post_nms_top_n=POST_NMS,
                          threshold=0.7, rpn_min_size=8)
    cls, box = net.head(feat, rois)
    prob = mx.nd.softmax(cls, axis=-1).asnumpy()
    rois_np = rois.asnumpy()
    boxes = apply_deltas(rois_np[:, 1:5], box.asnumpy())
    dets = [[] for _ in range(B)]
    for i in range(rois_np.shape[0]):
        c = prob[i, 1:].argmax()
        score = prob[i, 1 + c]
        if score >= score_thresh:
            dets[int(rois_np[i, 0])].append((c, score, *boxes[i]))
    return dets


def recall_metric(net, rng, n=32):
    imgs, labels = synthetic_scene(rng, n)
    dets = detect(net, imgs)
    hit = tot = 0
    for b in range(n):
        gts = labels[b][labels[b][:, 0] >= 0]
        tot += len(gts)
        for g in gts:
            for (c, _, x1, y1, x2, y2) in dets[b]:
                if c == int(g[0]) and iou_matrix(
                        np.array([[x1, y1, x2, y2]], np.float32),
                        g[None, 1:5])[0, 0] >= 0.5:
                    hit += 1
                    break
    return hit / max(tot, 1)


def train(steps=200, batch=4, lr=0.003, seed=0, verbose=True):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    net = FasterRCNN()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    anchors = anchor_grid()
    F = S // STRIDE
    im_info = mx.nd.array(
        np.tile([S, S, 1.0], (batch, 1)).astype(np.float32))

    for step in range(steps):
        imgs, labels = synthetic_scene(rng, batch)
        ts = [rpn_targets(anchors, labels[b]) for b in range(batch)]
        ct = np.stack([t[0] for t in ts])
        bt = np.stack([t[1] for t in ts])
        with autograd.record():
            feat = net.features(mx.nd.array(imgs))
            rpn_c, rpn_b = net.rpn(feat)
            # [B, 2A, F, F] -> [B, anchors, 2] logits (bg, fg)
            logits = rpn_c.reshape((batch, 2, A, F, F)) \
                .transpose((0, 3, 4, 2, 1)).reshape((batch, -1, 2))
            lp = mx.nd.log_softmax(logits, axis=-1)
            ctn = mx.nd.array(ct)
            keep = ctn >= 0
            picked = mx.nd.pick(lp, mx.nd.relu(ctn), axis=2)
            rpn_cls_loss = -(picked * keep).sum() / \
                mx.nd.clip(keep.sum(), 1, 1e9)
            deltas = rpn_b.reshape((batch, A, 4, F, F)) \
                .transpose((0, 3, 4, 1, 2)).reshape((batch, -1, 4))
            pos = (ctn == 1)
            rpn_box_loss = (mx.nd.smooth_l1(
                deltas - mx.nd.array(bt), scalar=3.0)
                * pos.expand_dims(2)).sum() / \
                mx.nd.clip(pos.sum() * 4, 1, 1e9)

            with autograd.pause():
                cp = mx.nd.softmax(logits, axis=-1) \
                    .reshape((batch, F, F, A, 2)) \
                    .transpose((0, 4, 3, 1, 2)) \
                    .reshape((batch, 2 * A, F, F))
                rois = mx.nd.Proposal(
                    cp, rpn_b, im_info, feature_stride=STRIDE,
                    scales=SCALES, ratios=RATIOS, rpn_pre_nms_top_n=64,
                    rpn_post_nms_top_n=POST_NMS, threshold=0.7,
                    rpn_min_size=8)
                rois_np = rois.asnumpy()
                rc, rb, rm = rcnn_targets(rois_np, labels)

            cls, box = net.head(feat, rois)
            lp2 = mx.nd.log_softmax(cls, axis=-1)
            rcnn_cls_loss = -mx.nd.pick(
                lp2, mx.nd.array(rc), axis=1).mean()
            rmn = mx.nd.array(rm).expand_dims(1)
            rcnn_box_loss = (mx.nd.smooth_l1(
                box - mx.nd.array(rb), scalar=3.0) * rmn).sum() / \
                mx.nd.clip(rmn.sum() * 4, 1, 1e9)
            loss = rpn_cls_loss + rpn_box_loss + rcnn_cls_loss \
                + rcnn_box_loss
        loss.backward()
        trainer.step(1)
        if verbose and (step + 1) % 40 == 0:
            print("step %d loss %.3f (rpn %.3f/%.3f rcnn %.3f/%.3f)"
                  % (step + 1, float(loss.asnumpy()),
                     float(rpn_cls_loss.asnumpy()),
                     float(rpn_box_loss.asnumpy()),
                     float(rcnn_cls_loss.asnumpy()),
                     float(rcnn_box_loss.asnumpy())))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    eval_rng = np.random.RandomState(999)
    net = train(steps=args.steps, verbose=not args.smoke)
    recall = recall_metric(net, eval_rng)
    print("detection recall (IoU>=0.5, class-matched): %.3f" % recall)
    if args.smoke:
        # an untrained detector scores ~0; the trained one must find
        # most rectangles
        assert recall > 0.5, recall
        print("OK")


if __name__ == "__main__":
    main()
