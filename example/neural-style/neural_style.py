#!/usr/bin/env python
"""Neural style transfer by image optimization (reference:
``example/neural-style/`` — nstyle.py: backprop into the IMAGE through
a frozen conv feature pyramid, matching content activations and style
Gram matrices).

Zero-egress: the feature pyramid is a fixed randomly-initialized conv
stack (random shallow conv features carry enough texture statistics for
toy style transfer), content is a synthetic shape image and style a
synthetic stripe texture.  The mechanics are exactly the reference's:
autograd w.r.t. the input tensor, Adam on pixels, content + weighted
Gram-matrix style losses, total-variation smoothing.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

S = 64


def make_images(seed=0):
    rng = np.random.RandomState(seed)
    content = np.zeros((1, 3, S, S), np.float32)
    content[0, :, 16:48, 16:48] = 1.0           # a bright square
    content += rng.normal(0, 0.02, content.shape)
    style = np.zeros((1, 3, S, S), np.float32)
    for i in range(0, S, 8):                    # diagonal stripes
        for j in range(S):
            style[0, :, (i + j) % S, j] = (i // 8) % 2
    style += rng.normal(0, 0.02, style.shape)
    return content, style


class FeaturePyramid(gluon.nn.Block):
    """Frozen random conv stack; returns activations at three depths."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = gluon.nn.Conv2D(16, 3, padding=1)
            self.c2 = gluon.nn.Conv2D(32, 3, padding=1, strides=2)
            self.c3 = gluon.nn.Conv2D(64, 3, padding=1, strides=2)

    def forward(self, x):
        f1 = mx.nd.relu(self.c1(x))
        f2 = mx.nd.relu(self.c2(f1))
        f3 = mx.nd.relu(self.c3(f2))
        return [f1, f2, f3]


def gram(f):
    B, C, H, W = f.shape
    m = f.reshape((C, H * W))
    return mx.nd.dot(m, m.transpose((1, 0))) / (C * H * W)


def transfer(steps=60, lr=0.1, style_weight=50.0, tv_weight=1e-3,
             seed=0, verbose=True):
    content_np, style_np = make_images(seed)
    net = FeaturePyramid()
    net.initialize(mx.init.Xavier(magnitude=2.0))
    content_feats = [f.detach() for f in net(mx.nd.array(content_np))]
    style_grams = [gram(f).detach() for f in net(mx.nd.array(style_np))]

    img = mx.nd.array(content_np.copy())
    img.attach_grad()
    # Adam state on the pixel tensor (reference uses an lr-decayed
    # optimizer on the image too)
    m_t = mx.nd.zeros(img.shape)
    v_t = mx.nd.zeros(img.shape)

    losses = []
    for t in range(steps):
        with autograd.record():
            feats = net(img)
            c_loss = ((feats[1] - content_feats[1]) ** 2).mean()
            s_loss = sum(((gram(f) - g) ** 2).sum()
                         for f, g in zip(feats, style_grams))
            tv = ((img[:, :, 1:, :] - img[:, :, :-1, :]) ** 2).mean() \
                + ((img[:, :, :, 1:] - img[:, :, :, :-1]) ** 2).mean()
            loss = c_loss + style_weight * s_loss + tv_weight * tv
        loss.backward()
        mx.nd.adam_update(img, img.grad, m_t, v_t, lr=lr, out=img)
        losses.append(float(loss))
        if verbose and t % 20 == 0:
            print("step %d loss %.4f (content %.4f style %.4f)"
                  % (t, losses[-1], float(c_loss), float(s_loss)))
    return img, losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--output", type=str, default=None)
    args = ap.parse_args()
    img, losses = transfer(steps=args.steps, verbose=not args.smoke)
    print("style-transfer loss %.4f -> %.4f" % (losses[0], losses[-1]))
    if args.output:
        import cv2

        arr = np.asarray(img.asnumpy()[0].transpose(1, 2, 0))
        arr = np.clip(arr * 255, 0, 255).astype(np.uint8)
        cv2.imwrite(args.output, arr)
    if args.smoke:
        assert losses[-1] < losses[0] * 0.5, losses
        print("OK")


if __name__ == "__main__":
    main()
