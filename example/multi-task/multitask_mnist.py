#!/usr/bin/env python
"""Multi-task training (reference: ``example/multi-task/example_multi_task.py``):
one trunk, two heads (digit class + parity), a Module with TWO label
inputs, and a custom composite metric reading both outputs.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


class MultiAccuracy(mx.metric.EvalMetric):
    """Per-head accuracy over (digit, parity) outputs."""

    HEADS = ("digit-acc", "parity-acc")

    def __init__(self):
        super().__init__("multi-accuracy")

    def reset(self):
        self.correct = [0, 0]
        self.total = [0, 0]

    def update(self, labels, preds):
        for i, (l, p) in enumerate(zip(labels, preds)):
            pred = p.asnumpy().argmax(1)
            lab = l.asnumpy().astype(int)
            self.correct[i] += int((pred == lab).sum())
            self.total[i] += len(lab)

    def get(self):
        return list(self.HEADS), [c / max(1, t) for c, t in
                                  zip(self.correct, self.total)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    n, side, n_cls = 512, 8, 4
    X = rng.uniform(0, 1, (n, 1, side, side)).astype(np.float32)
    Yd = rng.randint(0, n_cls, (n,)).astype(np.float32)
    X += 0.8 * Yd[:, None, None, None] / n_cls
    Yp = (Yd % 2).astype(np.float32)
    # parity leaves its own spatial signature (top-row stripe), so both
    # heads have learnable signal of comparable difficulty
    X[Yp == 1, :, 0, :] += 0.6

    data = mx.sym.Variable("data")
    trunk = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                               name="conv1")
    trunk = mx.sym.Activation(trunk, act_type="relu")
    trunk = mx.sym.Flatten(trunk)
    fc_digit = mx.sym.FullyConnected(trunk, num_hidden=n_cls,
                                     name="fc_digit")
    fc_par = mx.sym.FullyConnected(trunk, num_hidden=2, name="fc_par")
    head_d = mx.sym.SoftmaxOutput(fc_digit, name="digit")
    head_p = mx.sym.SoftmaxOutput(fc_par, name="parity")
    net = mx.sym.Group([head_d, head_p])

    it = mx.io.NDArrayIter(
        X, {"digit_label": Yd, "parity_label": Yp}, batch_size=64,
        shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("digit_label", "parity_label"))
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier(),
            eval_metric=MultiAccuracy())

    it.reset()
    metric = MultiAccuracy()
    mod.score(it, metric)
    names, vals_list = metric.get()
    for nm, v in zip(names, vals_list):
        print("%s: %.3f" % (nm, v), flush=True)
    if min(vals_list) < 0.8:
        raise SystemExit("multi-task training failed to converge")
    print("MULTITASK_OK", flush=True)


if __name__ == "__main__":
    main()
