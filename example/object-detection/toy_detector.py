#!/usr/bin/env python
"""Single-shot toy object detector (reference: ``example/ssd`` — the
detection family of the acceptance suite, scaled to a synthetic task).

A conv backbone predicts, per grid cell, an objectness score + box
offsets (the SSD head shape); training uses smooth-L1 on boxes +
sigmoid CE on objectness; inference decodes candidates and prunes them
with the ``box_nms`` contrib op.  Synthetic scenes (bright rectangles
on noise) keep it zero-egress; the smoke-test metric is mean IoU of the
top detection against ground truth.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

S = 32      # image size
G = 4       # grid cells per side (cell = 8 px)


def synthetic_scene(rng, n):
    """Images with ONE bright axis-aligned rectangle; boxes in corner
    format (xmin, ymin, xmax, ymax), normalized to [0, 1]."""
    imgs = rng.normal(0, 0.1, (n, 1, S, S)).astype(np.float32)
    boxes = np.zeros((n, 4), np.float32)
    for i in range(n):
        w, h = rng.randint(8, 16, 2)
        x0 = rng.randint(0, S - w)
        y0 = rng.randint(0, S - h)
        imgs[i, 0, y0:y0 + h, x0:x0 + w] += 1.0
        boxes[i] = (x0 / S, y0 / S, (x0 + w) / S, (y0 + h) / S)
    return imgs, boxes


def targets_from_boxes(boxes):
    """Assign each gt box to the grid cell containing its center;
    offsets are (cx, cy) within the cell + (w, h) in image units."""
    n = boxes.shape[0]
    obj = np.zeros((n, G, G), np.float32)
    off = np.zeros((n, 4, G, G), np.float32)
    cx = (boxes[:, 0] + boxes[:, 2]) / 2
    cy = (boxes[:, 1] + boxes[:, 3]) / 2
    gx = np.minimum((cx * G).astype(int), G - 1)
    gy = np.minimum((cy * G).astype(int), G - 1)
    for i in range(n):
        obj[i, gy[i], gx[i]] = 1
        off[i, 0, gy[i], gx[i]] = cx[i] * G - gx[i]
        off[i, 1, gy[i], gx[i]] = cy[i] * G - gy[i]
        off[i, 2, gy[i], gx[i]] = boxes[i, 2] - boxes[i, 0]
        off[i, 3, gy[i], gx[i]] = boxes[i, 3] - boxes[i, 1]
    return obj, off


def decode(scores, offs):
    """(N,G,G) scores + (N,4,G,G) offsets -> (N, G*G, 5) candidates
    [score, xmin, ymin, xmax, ymax] for box_nms."""
    n = scores.shape[0]
    gx, gy = np.meshgrid(np.arange(G), np.arange(G))
    cx = (gx[None] + offs[:, 0]) / G
    cy = (gy[None] + offs[:, 1]) / G
    w = offs[:, 2]
    h = offs[:, 3]
    cand = np.stack([scores,
                     cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                    axis=1)          # (N, 5, G, G)
    return cand.reshape(n, 5, -1).transpose(0, 2, 1)


def iou(a, b):
    x0 = np.maximum(a[0], b[0])
    y0 = np.maximum(a[1], b[1])
    x1 = np.minimum(a[2], b[2])
    y1 = np.minimum(a[3], b[3])
    inter = max(0.0, x1 - x0) * max(0.0, y1 - y0)
    ua = ((a[2] - a[0]) * (a[3] - a[1]) +
          (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / ua if ua > 0 else 0.0


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd

    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--num-examples", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    imgs, boxes = synthetic_scene(rng, args.num_examples)
    obj_t, off_t = targets_from_boxes(boxes)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),                       # 16
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),                       # 8
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),                       # 4 = G
            gluon.nn.Conv2D(5, 1))                       # head: obj+4
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    huber = gluon.loss.HuberLoss()

    nb = args.num_examples // args.batch_size
    for epoch in range(args.num_epochs):
        tot = 0.0
        for b in range(nb):
            sl = slice(b * args.batch_size, (b + 1) * args.batch_size)
            x = mx.nd.array(imgs[sl])
            to = mx.nd.array(obj_t[sl])
            tf = mx.nd.array(off_t[sl])
            with autograd.record():
                out = net(x)                      # (N, 5, G, G)
                s = mx.nd.slice_axis(out, axis=1, begin=0, end=1) \
                    .reshape((-1, G, G))
                o = mx.nd.slice_axis(out, axis=1, begin=1, end=5)
                l_obj = bce(s, to).mean()
                # box loss only on positive cells
                mask = to.reshape((-1, 1, G, G))
                l_box = huber(o * mask, tf * mask).mean()
                loss = l_obj + 5.0 * l_box
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print("Epoch[%d] loss=%.4f" % (epoch, tot / nb), flush=True)

    # ---- inference with box_nms ----
    test_imgs, test_boxes = synthetic_scene(rng, 64)
    out = net(mx.nd.array(test_imgs)).asnumpy()
    scores = 1.0 / (1.0 + np.exp(-out[:, 0]))
    cand = decode(scores, out[:, 1:5])
    kept = mx.nd.box_nms(mx.nd.array(cand), overlap_thresh=0.5,
                         valid_thresh=0.1, score_index=0,
                         coord_start=1).asnumpy()
    ious = []
    for i in range(kept.shape[0]):
        best = kept[i, 0]  # nms sorts by score
        if best[0] <= 0:
            ious.append(0.0)
            continue
        ious.append(iou(best[1:5], test_boxes[i]))
    miou = float(np.mean(ious))
    print("mean IoU of top detection: %.3f" % miou)
    assert np.isfinite(miou)


if __name__ == "__main__":
    main()
