#!/usr/bin/env python
"""Trainable SSD on synthetic scenes (reference: ``example/ssd`` —
train.py/symbol/legacy_vgg16_ssd_300 scaled down to a zero-egress task).

The real SSD machinery end to end:
* anchors from ``MultiBoxPrior`` at two feature scales,
* training targets (greedy bipartite match + hard-negative mining) from
  ``MultiBoxTarget``,
* softmax CE on mined classes + smooth-L1 on encoded offsets,
* inference decode + per-class NMS via ``MultiBoxDetection``,
* a small mAP-style matched-detection metric.

Synthetic scenes are colored rectangles on noise; class = color.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

S = 64            # image size
NUM_CLASSES = 3   # foreground classes (colors)
MAX_OBJ = 2

SIZES = [[0.25, 0.35], [0.55, 0.7]]   # anchor sizes per feature scale
RATIOS = [[1.0, 2.0, 0.5]] * 2


def synthetic_scene(rng, n):
    """RGB noise + up to MAX_OBJ colored rectangles.  Labels [n, MAX_OBJ, 5]
    rows of (class, xmin, ymin, xmax, ymax), -1 padded."""
    imgs = rng.normal(0, 0.08, (n, 3, S, S)).astype(np.float32)
    labels = np.full((n, MAX_OBJ, 5), -1.0, np.float32)
    for i in range(n):
        for j in range(rng.randint(1, MAX_OBJ + 1)):
            cls = rng.randint(0, NUM_CLASSES)
            w, h = rng.randint(14, 30, 2)
            x0 = rng.randint(0, S - w)
            y0 = rng.randint(0, S - h)
            imgs[i, cls, y0:y0 + h, x0:x0 + w] += 1.0
            labels[i, j] = (cls, x0 / S, y0 / S, (x0 + w) / S,
                            (y0 + h) / S)
    return imgs, labels


class SSDNet(gluon.nn.HybridBlock):
    """Tiny SSD: shared conv trunk, two detection scales with per-scale
    class + box heads (reference symbol/symbol_builder.py shape)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        a = len(SIZES[0]) - 1 + len(RATIOS[0])  # anchors per position
        self.num_anchors_per_pos = a
        with self.name_scope():
            self.trunk = gluon.nn.HybridSequential()
            for ch in (16, 32):
                self.trunk.add(gluon.nn.Conv2D(ch, 3, padding=1),
                               gluon.nn.Activation("relu"),
                               gluon.nn.MaxPool2D(2))
            self.scale1 = gluon.nn.HybridSequential()  # 16x16
            self.scale1.add(gluon.nn.Conv2D(32, 3, padding=1),
                            gluon.nn.Activation("relu"))
            self.down = gluon.nn.HybridSequential()    # -> 8x8
            self.down.add(gluon.nn.Conv2D(32, 3, padding=1),
                          gluon.nn.Activation("relu"),
                          gluon.nn.MaxPool2D(2))
            self.cls_heads = [gluon.nn.Conv2D(a * (NUM_CLASSES + 1), 3,
                                              padding=1, prefix="cls%d_" % i)
                              for i in range(2)]
            self.box_heads = [gluon.nn.Conv2D(a * 4, 3, padding=1,
                                              prefix="box%d_" % i)
                              for i in range(2)]
            for blk in self.cls_heads + self.box_heads:
                self.register_child(blk)

    def hybrid_forward(self, F, x):
        feats = []
        x = self.trunk(x)
        x = self.scale1(x)
        feats.append(x)
        feats.append(self.down(x))
        anchors, cls_preds, box_preds = [], [], []
        for i, feat in enumerate(feats):
            anchors.append(F.contrib.MultiBoxPrior(
                feat, sizes=SIZES[i], ratios=RATIOS[i]))
            c = self.cls_heads[i](feat)          # [B, A*(C+1), H, W]
            b = self.box_heads[i](feat)          # [B, A*4, H, W]
            cls_preds.append(
                F.reshape(F.transpose(c, (0, 2, 3, 1)),
                          (0, -1, NUM_CLASSES + 1)))
            box_preds.append(F.reshape(F.transpose(b, (0, 2, 3, 1)),
                                       (0, -1)))
        return (F.concat(*anchors, dim=1),
                F.concat(*cls_preds, dim=1),
                F.concat(*box_preds, dim=1))


def train(epochs=8, batch_size=16, n_train=128, lr=0.2, seed=0,
          verbose=True):
    rng = np.random.RandomState(seed)
    imgs, labels = synthetic_scene(rng, n_train)
    net = SSDNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss(rho=1.0)

    losses = []
    for epoch in range(epochs):
        ep_loss = 0.0
        for s in range(0, n_train, batch_size):
            x = mx.nd.array(imgs[s:s + batch_size])
            y = mx.nd.array(labels[s:s + batch_size])
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                # targets are data, not graph: the reference computes them
                # from detached predictions too (MultiBoxTarget has no
                # gradient)
                loc_t, loc_m, cls_t = mx.nd.contrib.MultiBoxTarget(
                    anchors, y, cls_preds.transpose((0, 2, 1)),
                    overlap_threshold=0.5, negative_mining_ratio=3,
                    negative_mining_thresh=0.5)
                # mask the ignore_label (-1) anchors out of the CE — a raw
                # -1 index would wrap onto the last class
                valid = cls_t >= 0
                n_pos = loc_m.sum() / 4.0 + 1e-6
                logp = mx.nd.log_softmax(cls_preds, axis=-1)
                ce = -mx.nd.pick(logp, cls_t * valid, axis=-1)  # [B, N]
                lc = (ce * valid).sum() / n_pos
                lb = box_loss(box_preds * loc_m, loc_t * loc_m).sum() \
                    / n_pos
                loss = lc + lb
            loss.backward()
            trainer.step(x.shape[0])
            ep_loss += float(loss.mean())
        losses.append(ep_loss / max(1, n_train // batch_size))
        if verbose:
            print("epoch %d loss %.4f" % (epoch, losses[-1]))
    return net, losses


def evaluate(net, seed=99, n=32, iou_thresh=0.5):
    """Matched-detection metric: fraction of gt boxes recovered by an
    NMS-survivor of the right class with IoU > 0.5 (recall), plus mean
    IoU of best matches."""
    rng = np.random.RandomState(seed)
    imgs, labels = synthetic_scene(rng, n)
    x = mx.nd.array(imgs)
    anchors, cls_preds, box_preds = net(x)
    cls_prob = mx.nd.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
    dets = mx.nd.contrib.MultiBoxDetection(
        cls_prob, box_preds, anchors, nms_threshold=0.45,
        force_suppress=False, nms_topk=50).asnumpy()

    matched, total, ious = 0, 0, []
    for i in range(n):
        for row in labels[i]:
            if row[0] < 0:
                continue
            total += 1
            best = 0.0
            for d in dets[i]:
                if d[0] < 0 or d[1] < 0.3:
                    continue
                if int(d[0]) != int(row[0]):
                    continue
                ix1 = max(d[2], row[1])
                iy1 = max(d[3], row[2])
                ix2 = min(d[4], row[3])
                iy2 = min(d[5], row[4])
                iw, ih = max(0, ix2 - ix1), max(0, iy2 - iy1)
                inter = iw * ih
                union = ((d[4] - d[2]) * (d[5] - d[3])
                         + (row[3] - row[1]) * (row[4] - row[2]) - inter)
                best = max(best, inter / union if union > 0 else 0.0)
            ious.append(best)
            if best > iou_thresh:
                matched += 1
    recall = matched / max(1, total)
    mean_iou = float(np.mean(ious)) if ious else 0.0
    return recall, mean_iou


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny run + assertions for CI")
    args = ap.parse_args()
    if args.smoke:
        net, losses = train(epochs=8, n_train=128, verbose=False)
        assert losses[-1] < losses[0] * 0.6, \
            "SSD loss did not fall: %s" % losses
        recall, mean_iou = evaluate(net, n=16)
        print("SMOKE ssd loss %.3f->%.3f recall %.2f mean_iou %.2f"
              % (losses[0], losses[-1], recall, mean_iou))
        assert recall > 0.5, "NMS-ed detections miss gt (recall %.2f)" \
            % recall
        assert mean_iou > 0.35, "detections don't overlap gt"
        print("OK")
        return
    net, losses = train(epochs=args.epochs)
    recall, mean_iou = evaluate(net)
    print("recall@0.5 %.3f  mean IoU %.3f" % (recall, mean_iou))


if __name__ == "__main__":
    main()
