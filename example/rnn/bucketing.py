#!/usr/bin/env python
"""Bucketed LSTM language model over variable-length sequences.

Reference: ``example/rnn/bucketing/`` — sentences are grouped into
length buckets; ``BucketingModule`` builds one executor per bucket and
shares parameters across them (``python/mxnet/module/bucketing_module.py``).

TPU-native note: each bucket key is a distinct static shape, so each
bucket compiles once into its own XLA module and is cached — the same
shape-bucketing strategy XLA itself demands for dynamic lengths (the
reference invented it to share memory pools; here it also kills
recompilation).  Synthetic Markov sentences, zero egress.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

BUCKETS = [8, 16, 24, 32]


def synthetic_sentences(vocab, n, seed=0):
    """Markov sentences with varied lengths (pad id 0 reserved)."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.full(vocab - 1, 0.05), size=vocab - 1)
    out = []
    for _ in range(n):
        ln = int(rng.choice([6, 10, 14, 20, 28, 31]))
        s = np.zeros(ln, np.int64)
        s[0] = rng.randint(1, vocab)
        for i in range(1, ln):
            s[i] = 1 + rng.choice(vocab - 1, p=trans[s[i - 1] - 1])
        out.append(s)
    return out


class BucketSentenceIter:
    """Minimal BucketSentenceIter (reference python/mxnet/rnn/io.py):
    pads each sentence up to its bucket, serves per-bucket batches with
    ``bucket_key`` stamped on the DataBatch."""

    def __init__(self, sentences, batch_size, mx):
        self.mx = mx
        self.batch_size = batch_size
        self.data = {b: [] for b in BUCKETS}
        for s in sentences:
            for b in BUCKETS:
                if len(s) <= b:
                    pad = np.zeros(b, np.int64)
                    pad[:len(s)] = s
                    self.data[b].append(pad)
                    break
        # bucket keys are SEQUENCE lengths (padded length - 1: the data
        # is tokens[:-1], the label tokens[1:])
        self.default_bucket_key = max(BUCKETS) - 1
        self.provide_data = [mx.io.DataDesc(
            "data", (self.default_bucket_key, batch_size))]
        self.provide_label = [mx.io.DataDesc(
            "softmax_label", (self.default_bucket_key, batch_size))]
        self.reset()

    def reset(self):
        self._plan = []
        for b, rows in self.data.items():
            arr = np.stack(rows) if rows else np.zeros((0, b), np.int64)
            for i in range(0, len(arr) - self.batch_size + 1,
                           self.batch_size):
                self._plan.append((b, arr[i:i + self.batch_size]))
        np.random.RandomState(1).shuffle(self._plan)
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        mx = self.mx
        if self._i >= len(self._plan):
            raise StopIteration
        b, chunk = self._plan[self._i]
        self._i += 1
        x = chunk[:, :-1] if chunk.shape[1] > 1 else chunk
        y = chunk[:, 1:] if chunk.shape[1] > 1 else chunk
        seq = b - 1
        return mx.io.DataBatch(
            [mx.nd.array(x.T.astype(np.float32))],
            [mx.nd.array(y.T.astype(np.float32))],
            bucket_key=seq,
            provide_data=[mx.io.DataDesc("data",
                                         (seq, self.batch_size))],
            provide_label=[mx.io.DataDesc("softmax_label",
                                          (seq, self.batch_size))])


def main():
    import mxnet_tpu as mx

    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=120)
    ap.add_argument("--num-sentences", type=int, default=1500)
    ap.add_argument("--emsize", type=int, default=48)
    ap.add_argument("--nhid", type=int, default=96)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.2)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    def sym_gen(bucket_key):
        data = mx.sym.Variable("data")      # (seq, batch)
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=args.vocab,
                                 output_dim=args.emsize, name="embed")
        rnn_params = mx.sym.Variable("lstm_parameters",
                                     init=mx.init.Normal(0.05))
        state = mx.sym.Variable("lstm_state", init=mx.init.Zero())
        cell = mx.sym.Variable("lstm_state_cell", init=mx.init.Zero())
        rnn = mx.sym.RNN(embed, parameters=rnn_params, state=state,
                         state_cell=cell, state_size=args.nhid,
                         num_layers=1, mode="lstm", name="lstm")
        pred = mx.sym.reshape(rnn, shape=(-1, args.nhid))
        pred = mx.sym.FullyConnected(pred, num_hidden=args.vocab,
                                     name="decoder")
        out = mx.sym.SoftmaxOutput(
            pred, mx.sym.reshape(label, shape=(-1,)), name="softmax")
        return out, ("data",), ("softmax_label",)

    sentences = synthetic_sentences(args.vocab, args.num_sentences)
    it = BucketSentenceIter(sentences, args.batch_size, mx)

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=it.default_bucket_key, context=ctx)
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})
    metric = mx.metric.Perplexity(ignore_label=0)
    for epoch in range(args.epochs):
        metric.reset()
        it.reset()
        for batch in it:
            # rebind per bucket_key happens inside BucketingModule
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        print("Epoch %d: %s" % (epoch, metric.get()), flush=True)
    name, ppl = metric.get()
    print("final perplexity %.2f (uniform would be %d)"
          % (ppl, args.vocab))
    assert np.isfinite(ppl) and ppl < args.vocab


if __name__ == "__main__":
    main()
