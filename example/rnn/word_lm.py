#!/usr/bin/env python
"""Word-level LSTM language model (reference: ``example/rnn/word_lm``).

Trains on a text file (``--data``) or, by default, a deterministic
synthetic corpus with real n-gram structure (zero-egress environment).
Uses the fused RNN op stack (``mx.sym.RNN``) + BucketingModule-free fixed
BPTT like the reference's word_lm default path.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402


def synthetic_corpus(vocab=200, length=20000, seed=0):
    """Markov-chain corpus: each token strongly conditions the next, so a
    working LM must reach far-below-uniform perplexity."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.full(vocab, 0.05), size=vocab)
    toks = np.zeros(length, dtype=np.int64)
    for i in range(1, length):
        toks[i] = rng.choice(vocab, p=trans[toks[i - 1]])
    return toks


def batchify(data, batch_size, bptt):
    nbatch = len(data) // batch_size
    data = data[:nbatch * batch_size].reshape(batch_size, nbatch).T
    xs, ys = [], []
    for i in range(0, nbatch - 1, bptt):
        seq = min(bptt, nbatch - 1 - i)
        if seq < bptt:
            break
        xs.append(data[i:i + seq])
        ys.append(data[i + 1:i + 1 + seq])
    return xs, ys


def main():
    import mxnet_tpu as mx

    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=str, default=None,
                    help="text file (tokens split on whitespace)")
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--emsize", type=int, default=64)
    ap.add_argument("--nhid", type=int, default=128)
    ap.add_argument("--nlayers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--bptt", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1.0)
    args = ap.parse_args()

    if args.data:
        with open(args.data) as f:
            words = f.read().split()
        vocab_map = {w: i for i, w in enumerate(sorted(set(words)))}
        tokens = np.array([vocab_map[w] for w in words], dtype=np.int64)
        args.vocab = len(vocab_map)
    else:
        tokens = synthetic_corpus(args.vocab)
    xs, ys = batchify(tokens, args.batch_size, args.bptt)

    # symbol: embed -> fused LSTM -> FC over vocab (reference word_lm)
    data = mx.sym.Variable("data")        # (bptt, batch)
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=args.vocab,
                             output_dim=args.emsize, name="embed")
    rnn_params = mx.sym.Variable("lstm_parameters",
                                 init=mx.init.Normal(0.05))
    state = mx.sym.Variable("lstm_state", init=mx.init.Zero())
    state_cell = mx.sym.Variable("lstm_state_cell", init=mx.init.Zero())
    rnn = mx.sym.RNN(embed, parameters=rnn_params, state=state,
                     state_cell=state_cell, state_size=args.nhid,
                     num_layers=args.nlayers, mode="lstm",
                     name="lstm")
    pred = mx.sym.reshape(rnn, shape=(-1, args.nhid))
    pred = mx.sym.FullyConnected(pred, num_hidden=args.vocab,
                                 name="decoder")
    out = mx.sym.SoftmaxOutput(pred, mx.sym.reshape(label, shape=(-1,)),
                               name="softmax")

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = mx.mod.Module(out, data_names=["data"],
                        label_names=["softmax_label"], context=ctx)
    # manual batch loop (the reference word_lm also hand-rolls it)
    mod.bind(data_shapes=[("data", (args.bptt, args.batch_size))],
             label_shapes=[("softmax_label",
                            (args.bptt, args.batch_size))],
             for_training=True)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})
    metric = mx.metric.Perplexity(ignore_label=None)
    for epoch in range(args.epochs):
        metric.reset()
        for x, y in zip(xs, ys):
            batch = mx.io.DataBatch([mx.nd.array(x.astype(np.float32))],
                                    [mx.nd.array(y.astype(np.float32))])
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        print("Epoch %d: %s" % (epoch, metric.get()), flush=True)
    name, ppl = metric.get()
    uniform = float(args.vocab)
    print("final perplexity %.2f (uniform would be %.0f)" % (ppl, uniform))
    assert np.isfinite(ppl)


if __name__ == "__main__":
    main()
