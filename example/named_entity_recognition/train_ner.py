#!/usr/bin/env python
"""Named-entity recognition with a BiLSTM tagger (reference:
``example/named_entity_recognition`` — BiLSTM sequence labeling over
word embeddings, scaled to a zero-egress task).

Per-token BIO tagging: embedding → bidirectional LSTM → per-token dense
softmax, trained with token-level cross-entropy (padding masked).  The
synthetic language marks entity spans with a trigger token followed by
2-3 tokens from an entity vocabulary; the tagger must emit B/I on the
span (context-dependent: the SAME entity tokens without a trigger are
O), which requires the recurrent state — a bag-of-tokens model cannot
solve it.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402

VOCAB = 120
TRIGGER = 110          # "Mr." / "in" style trigger token
ENT_LO, ENT_HI = 80, 110   # entity-capable tokens
SEQ = 24
TAGS = 3               # O=0, B=1, I=2


def make_data(rng, n):
    X = rng.randint(0, 80, (n, SEQ))
    Y = np.zeros((n, SEQ), np.int64)
    for i in range(n):
        # plant 1-2 triggered entity spans at DISJOINT positions (an
        # overlap would overwrite tokens while the first plant's labels
        # persist, contradicting the generative rule)
        used = np.zeros(SEQ, bool)
        for _ in range(rng.randint(1, 3)):
            ln = rng.randint(2, 4)
            for _try in range(10):
                p = rng.randint(0, SEQ - ln - 1)
                if not used[p:p + ln + 1].any():
                    break
            else:
                continue
            used[p:p + ln + 1] = True
            X[i, p] = TRIGGER
            X[i, p + 1:p + 1 + ln] = rng.randint(ENT_LO, ENT_HI, ln)
            Y[i, p + 1] = 1                      # B
            Y[i, p + 2:p + 1 + ln] = 2           # I
        # distractor: entity-range tokens WITHOUT a trigger stay O
        p = rng.randint(0, SEQ - 2)
        if X[i, p] != TRIGGER and (p == 0 or X[i, p - 1] != TRIGGER):
            X[i, p] = rng.randint(ENT_LO, ENT_HI)
    return X.astype(np.float32), Y


class BiLSTMTagger(gluon.nn.Block):
    def __init__(self, embed=32, hidden=48, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = gluon.nn.Embedding(VOCAB, embed)
            self.lstm = gluon.rnn.LSTM(hidden, num_layers=1,
                                       bidirectional=True, layout="NTC")
            self.out = gluon.nn.Dense(TAGS, flatten=False)

    def forward(self, tokens):
        return self.out(self.lstm(self.embed(tokens)))  # [B, T, TAGS]


def f1_entities(pred, gold):
    """Span-level F1: a predicted B..I span counts iff it exactly
    matches a gold span."""
    def spans(tags):
        out, i = set(), 0
        while i < len(tags):
            if tags[i] == 1:
                j = i + 1
                while j < len(tags) and tags[j] == 2:
                    j += 1
                out.add((i, j))
                i = j
            else:
                i += 1
        return out

    tp = fp = fn = 0
    for p, g in zip(pred, gold):
        ps, gs = spans(p), spans(g)
        tp += len(ps & gs)
        fp += len(ps - gs)
        fn += len(gs - ps)
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


def train(epochs=6, batch=32, lr=0.003, seed=0, verbose=True):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    Xtr, Ytr = make_data(rng, 512)
    Xte, Yte = make_data(rng, 256)
    net = BiLSTMTagger()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    for ep in range(epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for i in range(0, len(Xtr), batch):
            idx = perm[i:i + batch]
            xb = mx.nd.array(Xtr[idx])
            yb = mx.nd.array(Ytr[idx].astype(np.float32))
            with autograd.record():
                lp = mx.nd.log_softmax(net(xb), axis=-1)
                loss = -mx.nd.pick(lp, yb, axis=2).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        if verbose:
            with autograd.pause():
                pred = net(mx.nd.array(Xte)).asnumpy().argmax(-1)
            print("epoch %d loss %.3f span-F1 %.3f"
                  % (ep, tot / max(1, len(Xtr) // batch),
                     f1_entities(pred, Yte)))
    with autograd.pause():
        pred = net(mx.nd.array(Xte)).asnumpy().argmax(-1)
    return net, f1_entities(pred, Yte)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    net, f1 = train(epochs=args.epochs, verbose=not args.smoke)
    print("entity span F1: %.3f" % f1)
    if args.smoke:
        assert f1 > 0.8, f1
        print("OK")


if __name__ == "__main__":
    main()
