"""Benchmark: ResNet-50 training + inference throughput on one TPU chip.

Reference headline numbers (BASELINE.md, `docs/faq/perf.md`):
  * training  b128 fp32 V100: 363.69 img/s (`perf.md:236`)
  * inference b128 fp16 V100: 2355.04 img/s (`perf.md:192`)

This runs the same workload through the Gluon user path — model-zoo
ResNet-50 cast to bfloat16 (the TPU-native training dtype, with fp32 master
weights via the optimizer's multi-precision states), SoftmaxCrossEntropyLoss,
sgd+momentum — with the whole train step compiled to ONE XLA module
(`gluon.contrib.FusedTrainStep`).

Blackout-proof harness (docs/OBSERVABILITY.md): the round is a sequence of
independently budgeted LEGS.  Each leg runs under its own SIGALRM budget
(BENCH_LEG_BUDGET_<NAME> overrides the default), so a leg that blows its
budget times out ALONE — every other leg still runs and the round still
emits its records (round 5 of this repo produced rc 124 / zero data when
one global watchdog fired; never again).  Each leg's record is flushed
incrementally to BENCH_PARTIAL_PATH (default bench_partial.jsonl, one
JSON line per leg) the moment the leg ends, and the final single-line
JSON still always prints.  All legs share one process, so the persistent
XLA compile cache (MXNET_COMPILE_CACHE, armed before import) and every
in-process jit cache carry across legs.

Env knobs: BENCH_BATCH (default 128), BENCH_STEPS (default 30),
BENCH_MODEL (default resnet50_v1), BENCH_DTYPE (default bfloat16),
BENCH_BUDGET_S (global wall-clock ceiling, default 480; quick mode
defaults to 390 so the whole round clears an external kill timer),
BENCH_QUICK / --quick (small model, few steps; auto-enabled on ANY
non-TPU backend — r05's blackout was full mode running on an
experimental platform string), BENCH_KERNELS (Pallas kernel-program
leg, docs/KERNELS.md; on by default),
BENCH_LEGS (comma list: run only these legs), BENCH_LOADREPLAY
(trace-driven overload replay leg, docs/SIMULATION.md; on by default),
BENCH_FORCE_TIMEOUT_LEG
(burn the named leg's budget so its watchdog fires — the harness's own
regression test; BENCH_FORCE_TIMEOUT_S tunes the burn window, default
1.5s), BENCH_PARTIAL_PATH, BENCH_BASELINE /
BENCH_REGRESSION_STRICT (regression tripwire vs the last recorded
round: >10% drop on a leg metric is flagged; strict mode exits 3),
BENCH_COMPILE_CACHE (persistent XLA compile cache, on by default; 0
disables).  Always prints ONE parseable JSON line and exits 0 (3 only
in strict regression mode) — partial results carry per-leg status
markers instead of dying at rc 124.
"""
from __future__ import annotations

import json
import math
import os
import sys
import time

TRAIN_BASELINE_IMG_S = 363.69   # V100 fp32 b128 training, perf.md:236
INFER_BASELINE_IMG_S = 2355.04  # V100 fp16 b128 inference, perf.md:192

# Built progressively by main(); the __main__ wrapper prints it no
# matter how the run ends, so the driver always gets a JSON line.
RESULT = {
    "metric": "resnet50_train_img_per_sec",
    "value": 0.0,
    "unit": "images/sec",
    "vs_baseline": 0.0,
    "extra": {},
}

_T0 = time.monotonic()


class BudgetExceeded(Exception):
    """Raised by the SIGALRM watchdog and by in-loop budget checks."""


# SIGTERM (the driver's `timeout` sends it before SIGKILL) must shortcut
# straight to the summary line: r05 died at rc 124 with zero output
# because full-mode legs were still running when the term arrived.
_TERMINATED = False


def _term_handler(signum, frame):
    global _TERMINATED
    _TERMINATED = True
    raise BudgetExceeded("SIGTERM from driver")


def _budget_s():
    return float(os.environ.get("BENCH_BUDGET_S", "480"))


def _remaining():
    return _budget_s() - (time.monotonic() - _T0)


def _alarm_handler(signum, frame):
    raise BudgetExceeded("bench watchdog fired")


def _arm(seconds):
    """(Re)arm the SIGALRM watchdog for ``seconds`` (0 cancels).  Safe
    no-op off the main thread / on platforms without SIGALRM."""
    try:
        import signal

        if seconds:
            signal.signal(signal.SIGALRM, _alarm_handler)
            signal.alarm(max(1, int(math.ceil(seconds))))
        else:
            signal.alarm(0)
    except (ValueError, OSError, AttributeError):
        pass


# ---------------------------------------------------------------------------
# leg harness
# ---------------------------------------------------------------------------
def _partial_path():
    return os.environ.get("BENCH_PARTIAL_PATH", "bench_partial.jsonl")


def _reset_partial():
    try:
        with open(_partial_path(), "w"):
            pass
    except OSError:
        pass


def _flush_leg(name, status, record, elapsed):
    """Append this leg's record to the incremental JSONL file NOW — if a
    later leg (or the whole process) dies, everything measured so far is
    already on disk."""
    line = {"leg": name, "status": status,
            "elapsed_s": round(elapsed, 1), "record": record}
    try:
        with open(_partial_path(), "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
    except (OSError, TypeError, ValueError):
        pass


def _selected_legs():
    sel = os.environ.get("BENCH_LEGS", "").strip()
    if not sel:
        return None
    return {s.strip() for s in sel.split(",") if s.strip()}


def _quick_leg_budgets(legs, sel, budget_s):
    """Scale quick-mode leg budgets so the legs that will actually RUN
    collectively fit STRICTLY below 0.8x the outer budget — a
    worst-case round (every leg eats its allowance) must still end with
    legs marked, summary printed, rc 0, not an external kill.  Skipped
    legs (BENCH_LEGS subsets) keep their budgets and don't count toward
    the cap.  Floor at min(need, 45s): the compile-dominated CPU legs
    (sentinel ~37s, inference ~34s measured) must not be scaled below
    what a healthy run takes — but the floors may push the sum back
    over, so a final uniform shave re-asserts the strict bound.
    Returns (legs, scale-or-None)."""
    active = [leg for leg in legs if sel is None or leg[0] in sel]
    total_need = sum(need for _, _, need in active)
    cap = 0.8 * budget_s
    if total_need <= cap:
        return legs, None
    scale = cap / total_need
    scaled = {n: max(min(need, 45.0), need * scale)
              for n, _, need in active}
    floored = sum(scaled.values())
    if floored > cap:
        shave = cap / floored * 0.999
        scaled = {n: b * shave for n, b in scaled.items()}
    return [(n, f, scaled.get(n, need)) for n, f, need in legs], scale


def _leg_budget(name, default_need):
    try:
        return float(os.environ.get(
            "BENCH_LEG_BUDGET_" + name.upper(), default_need))
    except ValueError:
        return default_need


def _run_leg(extra, name, fn, need):
    """Run one leg under its own SIGALRM budget.  A timeout or error
    kills THIS leg only; its status lands in ``extra`` and the record
    (or lack of one) is flushed incrementally.  Returns the record dict
    on success, else None."""
    selected = _selected_legs()
    if selected is not None and name not in selected:
        extra[name + "_status"] = "skipped (BENCH_LEGS)"
        return None
    need = _leg_budget(name, need)
    remaining = _remaining()
    if remaining < min(need, 10.0):
        extra[name + "_status"] = "skipped (budget)"
        _flush_leg(name, "skipped (budget)", {}, 0.0)
        return None
    budget = min(need, remaining)
    forced = os.environ.get("BENCH_FORCE_TIMEOUT_LEG", "") == name
    if forced:
        try:
            burn = float(os.environ.get("BENCH_FORCE_TIMEOUT_S", "1.5"))
        except ValueError:
            burn = 1.5
        budget = min(budget, burn)
    t0 = time.monotonic()
    record, status = {}, "ok"
    _arm(budget)
    try:
        if forced:
            # burn this leg's budget so its watchdog fires: proves a
            # timed-out leg cannot take the round down with it
            while True:
                time.sleep(0.05)
        record = fn() or {}
    except BudgetExceeded:
        status = "timeout (leg budget %.0fs)" % budget
        if _TERMINATED:
            # the driver is tearing us down: flush this leg, then let the
            # exception reach __main__ so the summary prints within the
            # kill grace instead of starting another leg
            _flush_leg(name, "terminated", record,
                       time.monotonic() - t0)
            raise
    except Exception as e:  # one leg must never sink the round
        if _TERMINATED:
            # the handler's raise surfaced wrapped in another exception
            # (it can land inside arbitrary library code): still tear down
            _flush_leg(name, "terminated", record, time.monotonic() - t0)
            raise BudgetExceeded("SIGTERM from driver")
        status = "error: %s: %s" % (type(e).__name__, e)
    finally:
        # hand the watchdog back to the global ceiling between legs
        rem = _remaining()
        _arm(rem if rem > 0 else 1)
    elapsed = time.monotonic() - t0
    if status == "ok":
        extra.update(record)
    extra[name + "_status"] = status
    _flush_leg(name, status, record, elapsed)
    return record if status == "ok" else None


_EMITTED = False


def _emit_summary():
    """Print the single summary JSON line, exactly once, merging in any
    legs that only made it to the partial JSONL (a leg mid-flight when
    SIGTERM/SIGALRM hit has its record on disk but not in RESULT).
    Registered via atexit AND called from the __main__ finally, so every
    exit path short of SIGKILL produces a parseable line."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    extra = RESULT.setdefault("extra", {})
    if _TERMINATED:
        extra.setdefault("budget_exceeded", "SIGTERM from driver")
    try:
        with open(_partial_path()) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                name = rec.get("leg")
                if not name or (name + "_status") in extra:
                    continue
                extra[name + "_status"] = "%s (from partial)" % \
                    rec.get("status", "?")
                if rec.get("status") == "ok":
                    for k, v in (rec.get("record") or {}).items():
                        extra.setdefault(k, v)
    except OSError:
        pass
    print(json.dumps(RESULT))
    sys.stdout.flush()


# ---------------------------------------------------------------------------
# regression tripwire
# ---------------------------------------------------------------------------
_HIGHER_BETTER = ("_img_per_sec", "_per_sec", "_tokens_per_sec", "mfu",
                  "_vs_bf16", "_vs_naive", "_vs_baseline",
                  "_vs_v100_fp16", "value")
_LOWER_BETTER = ("_ms", "_reprefill_ratio")


def _flat_metrics(result):
    out = {}
    v = result.get("value")
    if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
        out["value"] = float(v)
    for k, val in (result.get("extra") or {}).items():
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            out[k] = float(val)
    return out


def _direction(key):
    for s in _HIGHER_BETTER:
        if key.endswith(s):
            return 1
    for s in _LOWER_BETTER:
        if key.endswith(s):
            return -1
    return 0


def check_regressions(result, baseline_path=None, threshold=0.10):
    """Compare this round's leg metrics against the last recorded round
    (BENCH_BASELINE, or the newest parseable BENCH_r*.json next to this
    script with a matching platform) and flag any metric that moved
    >``threshold`` in the bad direction — throughput/MFU drops, latency
    increases.  Returns {status, baseline, flagged:[...]}; never
    raises."""
    try:
        path = baseline_path or os.environ.get("BENCH_BASELINE", "")
        base = None
        if path:
            with open(path) as f:
                base = json.load(f)
        else:
            import glob

            here = os.path.dirname(os.path.abspath(__file__))
            for cand in sorted(glob.glob(os.path.join(here,
                                                      "BENCH_r*.json")),
                               reverse=True):
                try:
                    with open(cand) as f:
                        loaded = json.load(f)
                except (OSError, ValueError):
                    continue
                if isinstance(loaded, dict) and loaded.get("value"):
                    base, path = loaded, cand
                    break
        if not isinstance(base, dict):
            return {"status": "skipped (no baseline)"}
        bplat = (base.get("extra") or {}).get("platform")
        nplat = (result.get("extra") or {}).get("platform")
        if bplat != nplat:
            return {"status": "skipped (platform mismatch: baseline %s, "
                              "current %s)" % (bplat, nplat),
                    "baseline": os.path.basename(path)}
        old_m, new_m = _flat_metrics(base), _flat_metrics(result)
        flagged = []
        for key, old in sorted(old_m.items()):
            new = new_m.get(key)
            direction = _direction(key)
            if new is None or old <= 0 or direction == 0:
                continue
            drop = ((old - new) / old) * direction
            if drop > threshold:
                flagged.append({"metric": key,
                                "baseline": round(old, 4),
                                "current": round(new, 4),
                                "drop_pct": round(drop * 100.0, 1)})
        return {"status": "checked",
                "baseline": os.path.basename(path),
                "flagged": flagged}
    except Exception as e:
        return {"status": "error: %s: %s" % (type(e).__name__, e)}


# ---------------------------------------------------------------------------
# the round
# ---------------------------------------------------------------------------
def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="mxnet_tpu training/inference benchmark")
    ap.add_argument("--quick", action="store_true",
                    help="small model, few steps, primary legs only")
    cli, _ = ap.parse_known_args(argv)

    # Persistent XLA compile cache: armed BEFORE mxnet_tpu imports (the
    # cache only takes effect if configured before the first compile),
    # then shared by every leg in this round AND by the next round.
    if os.environ.get("BENCH_COMPILE_CACHE", "1") != "0":
        os.environ.setdefault("MXNET_COMPILE_CACHE", "auto")

    import numpy as np
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, profiler, telemetry
    from mxnet_tpu.gluon.contrib import FusedTrainStep
    from mxnet_tpu.gluon.model_zoo import vision

    platform = jax.default_backend()
    # quick: explicit flag/env wins; unset env auto-enables on ANY
    # non-TPU backend (the full sweep times out there — r05 ran full
    # mode because an experimental platform string wasn't "cpu" and
    # blacked out at rc 124); BENCH_QUICK=0 forces full.
    env_quick = os.environ.get("BENCH_QUICK", "")
    quick = (cli.quick or env_quick not in ("", "0")
             or (platform != "tpu" and env_quick != "0"))
    if quick and "BENCH_BUDGET_S" not in os.environ:
        # keep the whole quick round comfortably under the driver's
        # external kill timer; the per-leg watchdogs re-read this
        os.environ["BENCH_BUDGET_S"] = "390"

    batch = int(os.environ.get("BENCH_BATCH", "8" if quick else "128"))
    steps = int(os.environ.get("BENCH_STEPS", "5" if quick else "30"))
    model_name = os.environ.get(
        "BENCH_MODEL", "resnet18_v1" if quick else "resnet50_v1")
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    # quick shrinks the spatial size too: XLA's CPU backend takes minutes
    # to compile/execute the 224px train graph, which is exactly the rc-124
    # failure mode this mode exists to avoid
    size = int(os.environ.get("BENCH_SIZE", "56" if quick else "224"))
    reps = 2 if quick else 3

    ctx = mx.tpu() if platform not in ("cpu",) else mx.cpu()
    extra = RESULT["extra"]
    extra["platform"] = platform
    extra["quick"] = quick
    extra["compile_cache_dir"] = mx.runtime.compile_cache_dir()
    RESULT["metric"] = "%s_train_img_per_sec_b%d_%s_%s" % (
        model_name.split("_")[0], batch, dtype, platform)
    _reset_partial()

    # shared training context, built lazily INSIDE the first leg that
    # needs it (so BENCH_LEGS=serving,transformer never compiles resnet,
    # and the build time is charged to a leg budget, not the round)
    tctx = {}

    def host_fetch(arr):
        # materialize on host: the real execution barrier — the remote
        # runtime can acknowledge un-materialized buffers, which makes
        # barrier-only timings read impossibly fast
        arr.asnumpy()

    def ensure_train_ctx():
        if tctx:
            return tctx
        net = getattr(vision, model_name)(classes=1000)
        net.initialize(mx.init.Xavier(), ctx=ctx)
        net.hybridize(static_alloc=True, static_shape=True)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        rng = np.random.RandomState(0)
        x32 = mx.nd.array(rng.rand(batch, 3, size, size).astype(np.float32),
                          ctx=ctx)
        y = mx.nd.array(rng.randint(0, 1000, (batch,)), ctx=ctx)
        # finish deferred init in fp32, then cast the net to the compute
        # dtype (BatchNorm keeps its statistics in fp32; the optimizer
        # holds fp32 master weights — the reference's mp_sgd flow)
        with mx.autograd.pause():
            net(x32)
        multi_precision = dtype != "float32"
        if multi_precision:
            net.cast(dtype)
        x = x32.astype(dtype) if multi_precision else x32
        trainer = gluon.Trainer(
            net.collect_params(), "sgd",
            {"learning_rate": 0.05, "momentum": 0.9,
             "multi_precision": multi_precision})
        step = FusedTrainStep(net, loss_fn, trainer)
        for _ in range(2 if quick else 3):  # warmup: compile fwd+bwd+update
            loss = step(x, y)
        host_fetch(loss)
        tctx.update(net=net, loss_fn=loss_fn, trainer=trainer, step=step,
                    x=x, y=y)
        return tctx

    # ---- legs -----------------------------------------------------------
    def train_leg():
        c = ensure_train_ctx()
        step, x, y = c["step"], c["x"], c["y"]
        # best-of-N repetitions (remote-tunnel jitter); every timed
        # region ends with a HOST VALUE FETCH, not just a ready-barrier.
        # The train loop is naturally serialized through the donated
        # parameter chain.
        train_img_s = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(x, y)
            host_fetch(loss)
            dt = time.perf_counter() - t0
            train_img_s = max(train_img_s, batch * steps / dt)
            # publish after every rep so a watchdog flush has the best
            # so far
            RESULT["value"] = round(train_img_s, 2)
            RESULT["vs_baseline"] = round(
                train_img_s / TRAIN_BASELINE_IMG_S, 4)
            extra["train_steps_per_sec"] = round(train_img_s / batch, 2)
        out = {"loss_final": float(np.asarray(
            loss.asnumpy(), dtype=np.float32).mean())}
        # live cost-analysis gauges the step accountant published during
        # the loop (docs/OBSERVABILITY.md): MFU + HBM utilization with
        # zero device syncs
        gauges = telemetry.registry().snapshot()["gauges"]
        for src, dst in (("train.fused.mfu", "train_mfu"),
                         ("train.fused.hbm_util", "train_hbm_util"),
                         ("train.fused.items_per_sec",
                          "train_live_img_per_sec")):
            if src in gauges:
                out[dst] = round(gauges[src], 4)
        return out

    def sentinel_leg():
        # same net/trainer with the guard armed: the fused finiteness
        # reduction + lax.cond containment must stay within the 3%
        # acceptance budget (docs/NUMERICAL_HEALTH.md).  Interleaved
        # base/guard window pairs; the overhead is the MEDIAN per-pair
        # ratio — host interference lands on one window of one pair and
        # would be read as sentinel cost (or savings) by a mean or an
        # extreme, while the median pair is clean on a mostly-idle
        # machine.
        c = ensure_train_ctx()
        step, x, y = c["step"], c["x"], c["y"]
        guard_step = FusedTrainStep(c["net"], c["loss_fn"], c["trainer"],
                                    numeric_guard="skip")
        for _ in range(2 if quick else 3):  # warmup: separate module
            gloss = guard_step(x, y)
        host_fetch(gloss)
        win = max(2, steps // 2)
        guard_img_s, ratios = 0.0, []
        for _ in range(3 if quick else 3 * reps):
            dts = {}
            for tag, s in (("base", step), ("guard", guard_step)):
                t0 = time.perf_counter()
                for _ in range(win):
                    gloss = s(x, y)
                host_fetch(gloss)
                dts[tag] = time.perf_counter() - t0
            guard_img_s = max(guard_img_s, batch * win / dts["guard"])
            ratios.append(dts["guard"] / dts["base"] - 1.0)
        ratios.sort()
        mid = len(ratios) // 2
        overhead = (ratios[mid] if len(ratios) % 2
                    else (ratios[mid - 1] + ratios[mid]) / 2.0)
        return {"sentinel_guard_img_per_sec": round(guard_img_s, 2),
                "sentinel_overhead_pct": round(overhead * 100.0, 2)}

    def inference_leg():
        # two disciplines (mxnet_tpu/benchmark.py): the compiled K-step
        # loop (one dispatch per draw — measures the device, the gate
        # metric) and the per-dispatch user path (tunnel-sensitive,
        # published with its spread).
        from mxnet_tpu.benchmark import (compiled_throughput,
                                         percall_throughput)

        c = ensure_train_ctx()
        net, x = c["net"], c["x"]
        draws = 2 if quick else 5
        dev = compiled_throughput(net, x, steps=steps, draws=draws)
        percall = percall_throughput(net, x, steps=steps, draws=draws)
        tctx["infer_img_s"] = dev["median"]
        return {
            "inference_img_per_sec": round(dev["median"], 2),
            "inference_img_per_sec_spread": [round(dev["min"], 2),
                                             round(dev["max"], 2)],
            "inference_percall_img_per_sec": round(percall["median"], 2),
            "inference_percall_spread": [round(percall["min"], 2),
                                         round(percall["max"], 2)],
            "inference_vs_v100_fp16": round(
                dev["median"] / INFER_BASELINE_IMG_S, 4),
        }

    def serving_leg():
        return serving_bench(quick=quick)

    def latency_b1_leg():
        # batch-1 serving latency, 100 chained steps/dispatch so the
        # tunnel RTT amortizes away (docs/PERF_LATENCY.md)
        from mxnet_tpu.benchmark import compiled_throughput

        c = ensure_train_ctx()
        r1 = compiled_throughput(c["net"], c["x"][0:1], steps=100, draws=3)
        b1key = "latency_b1_%s" % model_name
        return {b1key + "_img_per_sec": round(r1["median"], 1),
                b1key + "_ms": round(1000.0 / r1["median"], 3)}

    def int8_leg():
        return int8_bench(batch=batch, steps=steps,
                          bf16_img_s=tctx.get("infer_img_s"))

    def transformer_leg():
        return transformer_bench(quick=quick)

    def decode_leg():
        return decode_bench(quick=quick)

    def kernels_leg():
        return kernels_bench(quick=quick)

    def racecheck_leg():
        return racecheck_bench(quick=quick)

    def longctx_leg():
        return long_context_bench()

    def fleet_leg():
        return fleet_bench(quick=quick)

    def gateway_leg():
        return gateway_bench(quick=quick)

    def loadreplay_leg():
        return loadreplay_bench(quick=quick)

    def migration_leg():
        return migration_bench(quick=quick)

    def tenant_leg():
        return tenant_bench(quick=quick)

    # quick (CPU-oracle) budgets are compile-dominated — the sentinel leg
    # builds a second XLA module — so some exceed their full-mode numbers
    legs = [
        ("train", train_leg, 150 if quick else 240),
        ("sentinel", sentinel_leg, 60 if quick else 45),
        ("inference", inference_leg, 45 if quick else 60),
        ("serving", serving_leg, 25 if quick else 45),
    ]
    if not quick:
        legs.append(("latency_b1", latency_b1_leg, 40))
        if os.environ.get("BENCH_INT8", "1") != "0":
            legs.append(("int8", int8_leg, 120))
    # the transformer leg runs in quick mode too: its record carries the
    # cost-analysis-derived "mfu", the number the observability layer is
    # accepted on
    if os.environ.get("BENCH_TRANSFORMER", "1") != "0":
        legs.append(("transformer", transformer_leg, 90 if quick else 120))
    # the decode leg runs in quick mode too: continuous-batching
    # generative inference is accepted on decode_tokens_per_sec / ttft_ms
    if os.environ.get("BENCH_DECODE", "1") != "0":
        legs.append(("decode", decode_leg, 60 if quick else 90))
    # the fleet leg runs in quick mode too: the sharded-serving +
    # autoscaling layer is accepted on fleet_scaleup_ms (lower-better
    # under the >10% regression tripwire) and the 2x-capacity shed rate
    if os.environ.get("BENCH_FLEET", "1") != "0":
        legs.append(("fleet", fleet_leg, 60 if quick else 120))
    # the gateway leg runs in quick mode too: the cross-process fleet is
    # accepted on gateway_route_p99_ms (lower-better) and the
    # burst-with-one-worker-killed gateway_kill_goodput_vs_baseline
    if os.environ.get("BENCH_GATEWAY", "1") != "0":
        legs.append(("gateway", gateway_leg, 90 if quick else 150))
    # the kernels leg runs in quick mode too: the Pallas kernel program
    # (flash fwd+bwd through the registry, int8 fused dequant) is
    # accepted on kernels_flash_vs_naive / kernels_int8_matmul_vs_bf16
    if os.environ.get("BENCH_KERNELS", "1") != "0":
        legs.append(("kernels", kernels_leg, 45 if quick else 90))
    # the racecheck leg runs in quick mode too: the armed lockset race
    # sanitizer is accepted on racecheck_checked_ops_per_sec (tripwired)
    # with racecheck_overhead_pct alongside; the off half of each pair
    # doubles as the off-mode zero-overhead baseline
    if os.environ.get("BENCH_RACECHECK", "1") != "0":
        legs.append(("racecheck", racecheck_leg, 20 if quick else 30))
    # the loadreplay leg runs in quick mode too: trace-driven overload
    # replay (docs/SIMULATION.md) is accepted on goodput at 2x measured
    # capacity and TTFT p99, both under the regression tripwire
    if os.environ.get("BENCH_LOADREPLAY", "1") != "0":
        legs.append(("loadreplay", loadreplay_leg, 45 if quick else 75))
    # the migration leg runs in quick mode too: live KV handoff
    # (docs/SHARDED_SERVING.md "Live migration") is accepted on
    # migrate_vs_reprefill_ratio at the longest context (lower-better
    # under the >10% tripwire; < 1.0 means the handoff beats re-prefill)
    if os.environ.get("BENCH_MIGRATION", "1") != "0":
        legs.append(("migration", migration_leg, 60 if quick else 150))
    # the tenant leg runs in quick mode too: the multi-tenant serving
    # plane is accepted on the deterministic SimFleet scale-up-lag A/B
    # (tenant_scaleup_lag_{reactive,predictive}_ms, lower-better under
    # the tripwire) with the noisy-neighbor isolation ratio alongside
    if os.environ.get("BENCH_TENANT", "1") != "0":
        legs.append(("tenant", tenant_leg, 75 if quick else 120))
    if not quick and os.environ.get("BENCH_LONGCTX", "1") != "0":
        legs.append(("longctx", longctx_leg, 150))
    if os.environ.get("BENCH_SERVING", "1") == "0":
        legs = [leg for leg in legs if leg[0] != "serving"]

    if quick:
        legs, scale = _quick_leg_budgets(legs, _selected_legs(),
                                         _budget_s())
        if scale is not None:
            extra["quick_budget_scale"] = round(scale, 3)

    for name, fn, need in legs:
        # the handler's raise can be swallowed by a broad except deep in a
        # leg (e.g. the cost-analysis probe) — the flag is authoritative
        if _TERMINATED:
            raise BudgetExceeded("SIGTERM from driver")
        _run_leg(extra, name, fn, need)

    extra["dispatch"] = profiler.dispatch_stats()
    extra["regression_check"] = check_regressions(RESULT)
    if extra["regression_check"].get("flagged"):
        # tripwire fired: capture a postmortem bundle so the regression
        # arrives with dispatch stats + recompile explanations attached
        from mxnet_tpu import debug as _debug

        _debug.write_bundle("bench_regression",
                            extra=extra["regression_check"])
    extra["elapsed_s"] = round(time.monotonic() - _T0, 1)


def serving_bench(quick=False):
    """Serving-front leg (docs/SERVING.md): batch-1 request latency
    p50/p99 through :class:`mxnet_tpu.serving.ModelServer` vs the bare
    ``Predictor.forward`` loop on the SAME model in the SAME process
    (drift-immune overhead reading), plus the shed rate under a
    synthetic burst at 4x the admission cap.  The served p50/p99 are
    read from the telemetry layer's ``serving.latency_ms`` histogram —
    the same numbers a production scrape of the registry reports."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import serving, telemetry
    from mxnet_tpu.predict import Predictor

    n_req = 100 if quick else 400
    max_queue = 32
    rng = np.random.RandomState(0)

    # small MLP: the front's overhead is model-independent bookkeeping,
    # so a short forward makes the p99 delta legible instead of noise
    d_in, d_h = 64, 256
    data = mx.sym.var("data")
    w1, b1 = mx.sym.var("fc1_weight"), mx.sym.var("fc1_bias")
    w2, b2 = mx.sym.var("fc2_weight"), mx.sym.var("fc2_bias")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, w1, b1, num_hidden=d_h, name="fc1"),
        act_type="relu")
    sym = mx.sym.FullyConnected(h, w2, b2, num_hidden=8, name="fc2")
    params = {
        "arg:fc1_weight": mx.nd.array(
            (rng.rand(d_h, d_in) * 0.1).astype(np.float32)),
        "arg:fc1_bias": mx.nd.zeros((d_h,)),
        "arg:fc2_weight": mx.nd.array(
            (rng.rand(8, d_h) * 0.1).astype(np.float32)),
        "arg:fc2_bias": mx.nd.zeros((8,)),
    }
    xs = [rng.rand(1, d_in).astype(np.float32) for _ in range(16)]

    def pctl(lat_s, q):
        return round(float(np.percentile(np.asarray(lat_s), q)) * 1e3, 3)

    # -- bare Predictor loop (the overhead baseline) --
    bare = Predictor(sym, dict(params), input_shapes={"data": (1, d_in)})
    for x in xs:
        bare.forward(data=mx.nd.array(x))[0].asnumpy()  # warm
    bare_lat = []
    for i in range(n_req):
        t0 = time.perf_counter()
        bare.forward(data=mx.nd.array(xs[i % len(xs)]))[0].asnumpy()
        bare_lat.append(time.perf_counter() - t0)

    out = {"serving_bare_p50_ms": pctl(bare_lat, 50),
           "serving_bare_p99_ms": pctl(bare_lat, 99)}

    # -- steady state through the serving front (no faults) --
    # max_wait 0: a closed-loop sequential client would otherwise spend
    # every request waiting out the batching timer, which would read as
    # front overhead when it is really idle batching slack
    hist = telemetry.registry().histogram("serving.latency_ms")
    srv = serving.ModelServer(sym, dict(params),
                              input_shapes={"data": (1, d_in)},
                              max_queue=max_queue, max_batch=8,
                              max_wait_ms=0, deadline_ms=30_000)
    try:
        for x in xs:
            srv.submit({"data": x})  # settle the EWMA + caches
        hist.reset()                 # measurement window starts here
        for i in range(n_req):
            srv.submit({"data": xs[i % len(xs)]})
        hs = hist.snapshot()
        out["serving_p50_ms"] = round(hs["p50"], 3)
        out["serving_p99_ms"] = round(hs["p99"], 3)
        out["serving_latency_count"] = hs["count"]
        out["serving_overhead_p99_pct"] = round(
            (out["serving_p99_ms"] / max(out["serving_bare_p99_ms"], 1e-9)
             - 1.0) * 100.0, 1)

        # -- burst at 4x the admission cap: shedding, not collapse --
        hist.reset()
        futs, shed = [], 0
        offered = 4 * max_queue
        for i in range(offered):
            try:
                futs.append(srv.submit_async(
                    {"data": xs[i % len(xs)]}, deadline_ms=30_000))
            except serving.Overloaded:
                shed += 1
        for f in futs:
            f.result(timeout=60)
        out["serving_burst_offered"] = offered
        out["serving_shed_rate"] = round(shed / offered, 4)
        out["serving_burst_p99_ms"] = round(
            hist.snapshot()["p99"] or 0.0, 3)
        snap = srv.snapshot()
        out["serving_queue_depth_peak"] = snap["queue_depth_peak"]
        out["serving_batches"] = {
            k: snap[k] for k in ("batches_full", "batches_timer",
                                 "batches_deadline")}
    finally:
        srv.drain(timeout=30)
    return out


def racecheck_bench(quick=False):
    """Racecheck leg (docs/STATIC_ANALYSIS.md "Data-race detection"):
    cost of the armed lockset detector over a representative tracked
    critical section — a tracked counter bumped under a held lock, the
    shape every serving-stack stats field has — vs the same class with
    the sanitizer uninstalled (no hooks exist, so the baseline IS the
    off-mode zero-overhead path the tests pin).  The on-window seeds the
    field into shared-modified first so every access pays the full
    lockset-intersection step, not the cheap exclusive-phase one.
    Interleaved off/on window pairs; the overhead is the MEDIAN per-pair
    ratio, same discipline as the sentinel leg.  The tripwire gates on
    ``racecheck_checked_ops_per_sec``."""
    import threading as _threading

    from mxnet_tpu import racecheck

    if racecheck.installed():
        # the round itself is running under MXTPU_RACECHECK: there is no
        # off window to pair against, so the leg carries no number
        return {"racecheck_skipped": "sanitizer already armed"}

    @racecheck.track("ctr")
    class _Counter:
        def __init__(self):
            self.ctr = 0

    ops = 20_000 if quick else 100_000
    reps = 3 if quick else 5

    def window(box, lk):
        t0 = time.perf_counter()
        for _ in range(ops):
            with lk:
                box.ctr += 1
        return time.perf_counter() - t0

    checked_ops_s, ratios = 0.0, []
    for _ in range(reps):
        box, lk = _Counter(), _threading.Lock()
        dt_off = window(box, lk)
        racecheck.install("record")
        try:
            box = _Counter()
            lk = racecheck._LockToken(_threading._allocate_lock(),
                                      "bench.py:0", "Lock")

            def seed():
                with lk:
                    box.ctr = 0    # second thread: leave exclusive phase

            t = _threading.Thread(target=seed)
            t.start()
            t.join()
            dt_on = window(box, lk)
            races = racecheck.snapshot()["counters"]["races"]
        finally:
            racecheck.uninstall()
            racecheck.reset()
        if races:                  # the bench loop is lock-disciplined
            return {"racecheck_error": "false race in bench loop"}
        checked_ops_s = max(checked_ops_s, ops / dt_on)
        ratios.append(dt_on / dt_off - 1.0)
    ratios.sort()
    mid = len(ratios) // 2
    overhead = (ratios[mid] if len(ratios) % 2
                else (ratios[mid - 1] + ratios[mid]) / 2.0)
    return {"racecheck_checked_ops_per_sec": round(checked_ops_s, 1),
            "racecheck_overhead_pct": round(overhead * 100.0, 2)}


def decode_bench(quick=False):
    """Generative-decode leg (docs/GENERATIVE.md): continuous-batching
    token generation through :class:`mxnet_tpu.generation.GenerationServer`
    — paged KV cache, prefill/decode split, iteration-level scheduler.
    Reports steady-state ``decode_tokens_per_sec`` (median of the
    per-iteration histogram over the measurement window),
    ``ttft_ms`` (submit -> first streamed token, prefill-dominated), and
    ``kv_page_util`` (allocator peak over the run).  The server warms
    every (prefill, slot) bucket before the window, so the window itself
    must be compile-free — the recompile counter delta is reported so the
    tripwire catches a bucketing regression as well as a throughput one."""
    import jax
    import numpy as np

    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.generation import GenerationConfig, GenerationServer
    from mxnet_tpu.models import TransformerConfig, TransformerLM

    vocab = 1024
    cfg = TransformerConfig(vocab_size=vocab, d_model=128, n_heads=4,
                            n_layers=2, d_ff=256, max_len=128,
                            dtype="float32", remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))

    max_new = 16 if quick else 32
    gcfg = GenerationConfig(page_size=16, max_pages=128,
                            max_slots=4 if quick else 8,
                            max_new_tokens=max_new)
    n_req = 8 if quick else 32
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, vocab, size=4 + (i * 5) % 21).astype(np.int32)
               for i in range(n_req + 2)]

    reg = telemetry.registry()
    ttft = reg.histogram("gen.ttft_ms")
    tps = reg.histogram("gen.decode_tokens_per_sec")
    srv = GenerationServer(model, params, gcfg)
    out = {}
    try:
        for p in prompts[:2]:
            srv.submit(p, max_new_tokens=4)      # settle the host paths
        base_recompiles = profiler.dispatch_value("recompile")
        base_tokens = profiler.dispatch_value("gen_tokens")
        ttft.reset()
        tps.reset()                              # window starts here
        t0 = time.perf_counter()
        futs = [srv.submit_async(p, max_new_tokens=max_new)
                for p in prompts[2:]]
        for f in futs:
            f.result(timeout=300)
        wall = time.perf_counter() - t0
        toks = profiler.dispatch_value("gen_tokens") - base_tokens
        hs_tps, hs_ttft = tps.snapshot(), ttft.snapshot()
        out["decode_tokens_per_sec"] = round(hs_tps["p50"] or 0.0, 1)
        out["decode_wall_tokens_per_sec"] = round(toks / wall, 1)
        out["decode_tokens_total"] = int(toks)
        out["ttft_ms"] = round(hs_ttft["p50"] or 0.0, 3)
        out["ttft_p99_ms"] = round(hs_ttft["p99"] or 0.0, 3)
        out["kv_page_util"] = round(srv.engine.allocator.peak_util, 4)
        out["decode_recompiles_in_window"] = int(
            profiler.dispatch_value("recompile") - base_recompiles)
    finally:
        srv.drain(timeout=30)
    return out


def migration_bench(quick=False):
    """Live KV-migration leg (docs/SHARDED_SERVING.md "Live
    migration"): at each context length, a greedy stream is parked
    mid-decode and restored on a sibling server two ways — the live
    handoff (export -> import -> attach, no prefill) and the journal
    re-prefill (``resume_from``) — measuring park-to-next-token latency
    for both.  Reports per-context ``migrate_ctx<N>_ms`` /
    ``reprefill_ctx<N>_ms`` and the headline
    ``migrate_vs_reprefill_ratio`` at the LONGEST context (lower-better
    under the >10% tripwire): re-prefill grows with the attention
    window while the handoff moves pages, so the ratio must stay below
    1 at long contexts — migration earning its keep."""
    import threading

    import jax
    import numpy as np

    from mxnet_tpu.generation import GenerationConfig, GenerationServer
    from mxnet_tpu.models import TransformerConfig, TransformerLM
    from mxnet_tpu.serving import StreamMigrated

    vocab = 1024
    max_len = 576 if quick else 1024
    ctxs = (96, 512) if quick else (96, 256, 512, 896)
    cfg = TransformerConfig(vocab_size=vocab, d_model=128, n_heads=4,
                            n_layers=2, d_ff=256, max_len=max_len,
                            dtype="float32", remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    gcfg = GenerationConfig(page_size=16, max_pages=48 if quick else 64,
                            max_slots=4, max_new_tokens=16)
    a = GenerationServer(model, params, gcfg)
    b = GenerationServer(model, params, gcfg)
    rng = np.random.RandomState(0)

    def parked(prompt):
        fut = a.submit_async(prompt, temperature=0.0)
        while len(fut.stream_tokens) < 4:
            time.sleep(0.001)
        [handle] = a.park_streams(1)
        try:
            fut.result(timeout=30)
        except StreamMigrated:
            pass
        return handle, fut.stream_tokens

    def t_first(submit):
        # park-to-next-token: the client-visible gap each path leaves
        evt = threading.Event()
        t0 = time.perf_counter()
        fut = submit(lambda t: evt.set())
        if not evt.wait(120):
            raise TimeoutError("no continuation token within 120s")
        dt = (time.perf_counter() - t0) * 1e3
        fut.result(timeout=120)
        return dt

    out = {}
    reps = 3
    try:
        for ctx in ctxs:
            prompt = rng.randint(0, vocab, size=ctx).astype(np.int32)
            # warm every path at this context: both prefill buckets,
            # the export/import/attach machinery, the resume re-prefill
            a.submit(prompt, max_new_tokens=4)
            b.submit(prompt, max_new_tokens=4)
            handle, deliv = parked(prompt)
            h2 = b.import_stream(a.export_stream(handle))
            b.submit_async(prompt, resume_from=deliv, migrate_handle=h2,
                           temperature=0.0).result(timeout=120)
            b.submit_async(prompt, resume_from=deliv,
                           temperature=0.0).result(timeout=120)
            mig, rep = [], []
            for _ in range(reps):
                handle, deliv = parked(prompt)

                def migrate(cb, handle=handle, deliv=deliv):
                    h2 = b.import_stream(a.export_stream(handle))
                    return b.submit_async(
                        prompt, resume_from=deliv, migrate_handle=h2,
                        temperature=0.0, on_token=cb)

                mig.append(t_first(migrate))
                rep.append(t_first(
                    lambda cb, deliv=deliv: b.submit_async(
                        prompt, resume_from=deliv, temperature=0.0,
                        on_token=cb)))
            out["migrate_ctx%d_ms" % ctx] = round(min(mig), 3)
            out["reprefill_ctx%d_ms" % ctx] = round(min(rep), 3)
        last = ctxs[-1]
        out["migrate_vs_reprefill_ratio"] = round(
            out["migrate_ctx%d_ms" % last]
            / out["reprefill_ctx%d_ms" % last], 4)
        out["migration_ctx_longest"] = last
    finally:
        a.drain(timeout=30)
        b.drain(timeout=30)
    return out


def loadreplay_bench(quick=False):
    """Trace-driven load-replay leg (docs/SIMULATION.md): a seeded
    :mod:`mxnet_tpu.loadgen` trace replayed at ~2x measured capacity
    against a real in-process :class:`GenerationServer` — the
    steady-overload profile the bounded admission queue must shed, not
    absorb.  Accepted on ``loadreplay_goodput_per_sec`` (sustained
    completions under overload, higher-better) and
    ``loadreplay_ttft_p99_ms`` (lower-better), both under the >10%
    regression tripwire; ``loadreplay_shed_rate`` documents how much of
    the offered load was typed ``Overloaded`` rather than absorbed."""
    import jax

    from mxnet_tpu import loadgen
    from mxnet_tpu.generation import GenerationConfig, GenerationServer
    from mxnet_tpu.models import TransformerConfig, TransformerLM

    vocab = 1024
    cfg = TransformerConfig(vocab_size=vocab, d_model=128, n_heads=4,
                            n_layers=2, d_ff=256, max_len=128,
                            dtype="float32", remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    max_new = 8 if quick else 16
    gcfg = GenerationConfig(page_size=16, max_pages=128,
                            max_slots=4 if quick else 8,
                            max_new_tokens=max_new)
    srv = GenerationServer(model, params, gcfg, max_queue=8)
    out = {}
    try:
        # calibrate: an uncontended asap burst measures capacity (the
        # warmup doubles as compile settling for every bucket touched)
        cal_spec = loadgen.TraceSpec(
            seed=11, segments=[{"duration_s": 1.0,
                                "rate_rps": 8.0 if quick else 16.0}],
            prompt_len_mean=6.0, prompt_len_max=24,
            output_len_mean=float(max_new), output_len_max=max_new,
            deadline_classes=[{"name": "cal", "deadline_ms": 60000.0,
                               "weight": 1.0}])
        target = loadgen.generation_target(srv, vocab=vocab)
        cal = loadgen.replay(loadgen.generate_trace(cal_spec), target,
                             speed=float("inf"), name="calibrate")
        n_ok = cal.outcome_counts().get("ok", 0)
        if not n_ok:
            out["loadreplay_status_detail"] = "calibration produced " \
                "no completions: %s" % cal.outcome_counts()
            return out
        capacity_rps = max(0.5, n_ok / max(cal.wall_s, 1e-6))
        out["loadreplay_capacity_rps"] = round(capacity_rps, 2)

        # the measured leg: 2x capacity offered for a few wall seconds
        dur = 4.0 if quick else 8.0
        spec = loadgen.TraceSpec(
            seed=12,
            segments=[{"duration_s": dur,
                       "rate_rps": 2.0 * capacity_rps}],
            prompt_len_mean=6.0, prompt_len_max=24,
            output_len_mean=float(max_new), output_len_max=max_new,
            deadline_classes=[{"name": "std", "deadline_ms": 8000.0,
                               "weight": 1.0}])
        report = loadgen.replay(loadgen.generate_trace(spec), target,
                                speed=1.0, name="loadreplay")
        out.update(report.summary())
        out["loadreplay_knee_rps"] = loadgen.shed_knee(report.curve())
    finally:
        srv.drain(timeout=30)
    return out


def tenant_bench(quick=False):
    """Multi-tenant serving leg (docs/SHARDED_SERVING.md "Multi-tenant
    serving").  Two halves:

    * isolation — a three-tenant weighted trace replayed against a real
      in-process :class:`GenerationServer` twice on the same seed: once
      clean, once with a mid-burst ``tenant_flood`` storm from the
      tightly quota'd ``bulk`` tenant.  ``tenant_isolation_ratio`` is
      the victim (gold/free) TTFT p99 under flood over clean — 1.0 is
      perfect isolation — and ``tenant_flood_shed_rate`` how much of
      the flooder's offered load was typed ``QuotaExceeded``.  Both are
      wall-clock noisy on a shared box, so neither carries a tripwire
      suffix; the strict deterministic <10% proof is the SimFleet test
      in tests/test_tenancy.py.
    * scale-up lag A/B — the same seeded burst trace through SimFleet
      reactive then predictive.  ``tenant_scaleup_lag_reactive_ms`` /
      ``tenant_scaleup_lag_predictive_ms`` (mean ms from first raw
      breach tick to the scale-up fire; 0 = capacity ordered before the
      breach) are fully deterministic, so both sit under the >10%
      lower-better regression tripwire.
    """
    import jax

    from mxnet_tpu import loadgen, serving, simfleet, tenancy
    from mxnet_tpu.generation import GenerationConfig, GenerationServer
    from mxnet_tpu.models import TransformerConfig, TransformerLM

    out = {}
    tenants = [{"name": "gold", "weight": 4},
               {"name": "free", "weight": 2},
               {"name": "bulk", "weight": 1}]

    # -- isolation: real server, quota-contained flood ----------------
    vocab = 1024
    cfg = TransformerConfig(vocab_size=vocab, d_model=128, n_heads=4,
                            n_layers=2, d_ff=256, max_len=128,
                            dtype="float32", remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    max_new = 8 if quick else 16
    gcfg = GenerationConfig(page_size=16, max_pages=128,
                            max_slots=4 if quick else 8,
                            max_new_tokens=max_new)
    srv = GenerationServer(model, params, gcfg, max_queue=16)
    try:
        spec = loadgen.TraceSpec(
            seed=31,
            segments=[{"duration_s": 4.0 if quick else 6.0,
                       "rate_rps": 8.0 if quick else 12.0}],
            prompt_len_mean=6.0, prompt_len_max=24,
            output_len_mean=float(max_new), output_len_max=max_new,
            tenants=tenants)
        trace = loadgen.generate_trace(spec)
        target = loadgen.generation_target(srv, vocab=vocab)
        # warm every prefill bucket before anything is timed (no
        # governor yet: nothing sheds during warmup)
        loadgen.replay(trace, target, speed=float("inf"), name="warm")

        def run(chaos_spec):
            tenancy.reset_governor(tenancy.TenantGovernor(
                quotas={"bulk": tenancy.TenantSpec("bulk", rate=2.0,
                                                   burst=2.0)}))
            serving.brownout().reset()
            try:
                if chaos_spec:
                    from mxnet_tpu import chaos
                    with chaos.inject(chaos_spec):
                        return loadgen.replay(trace, target, speed=2.0,
                                              name="tenant")
                return loadgen.replay(trace, target, speed=2.0,
                                      name="tenant")
            finally:
                tenancy.reset_governor()
                serving.brownout().reset()

        def victim_p99(report):
            ttfts = [r["ttft_ms"] for r in report.records
                     if r["tenant"] in ("gold", "free")
                     and r["outcome"] == "ok"
                     and r["ttft_ms"] is not None]
            return loadgen._pctl(ttfts, 99) if ttfts else None

        base = run(None)
        bulk_idx = [i for i, r in enumerate(trace)
                    if r["tenant"] == "bulk"]
        steps = bulk_idx[len(bulk_idx) // 2:len(bulk_idx) // 2 + 3]
        flood = run(",".join("tenant_flood@%d" % s for s in steps))

        p99_base, p99_flood = victim_p99(base), victim_p99(flood)
        if p99_base and p99_flood:
            out["tenant_isolation_ratio"] = round(p99_flood / p99_base,
                                                  4)
        else:
            out["tenant_status_detail"] = ("victims produced no ok "
                                           "TTFTs: base=%s flood=%s"
                                           % (base.outcome_counts(),
                                              flood.outcome_counts()))
        bulk = flood.tenant_summary().get("bulk", {})
        out["tenant_flood_shed_rate"] = round(
            bulk.get("shed_quota", 0) / max(1, bulk.get("requests", 1)),
            4)
    finally:
        srv.drain(timeout=30)

    # -- scale-up lag: reactive vs predictive on one seeded trace -----
    burst = loadgen.generate_trace(loadgen.TraceSpec(
        seed=33, segments=[{"duration_s": 3.0, "rate_rps": 2.0},
                           {"duration_s": 6.0, "rate_rps": 60.0}]))

    def lags(predict):
        tenancy.reset_governor(tenancy.TenantGovernor(quotas={}))
        serving.brownout().reset()
        try:
            with simfleet.SimFleet(burst, initial_replicas=2,
                                   max_replicas=12, seed=5,
                                   predict=predict,
                                   predict_horizon_s=4.0,
                                   predict_depth_up=6) as fleet:
                res = fleet.run()
        finally:
            tenancy.reset_governor()
            serving.brownout().reset()
        return res["supervisor"]["scaleup_lags_ms"]

    r_lags, p_lags = lags(False), lags(True)
    if r_lags:
        out["tenant_scaleup_lag_reactive_ms"] = round(
            sum(r_lags) / len(r_lags), 1)
    if p_lags:
        out["tenant_scaleup_lag_predictive_ms"] = round(
            sum(p_lags) / len(p_lags), 1)
    out["tenant_scaleups_reactive"] = len(r_lags)
    out["tenant_scaleups_predictive"] = len(p_lags)
    return out


def kernels_bench(quick=False):
    """Pallas kernel-program leg (docs/KERNELS.md): measures the two
    tentpole kernels through the SAME ``select_impl`` registry the model
    paths use, so the number tracks whatever implementation the backend
    actually gets (Pallas on TPU, lax fallbacks elsewhere — the quick/CPU
    reading gates plumbing regressions, the TPU reading gates the
    kernels).  Both are wrapped as ``kernel_unit`` TrackedJits, so the
    flight recorder and MFU attribution see them as ``kernel.*`` units.

    * flash attention forward+backward (``jax.value_and_grad`` through
      the custom VJP) in tokens/sec, against a naive materialized-scores
      attention with the same loss — ``kernels_flash_vs_naive``;
    * int8 matmul with fused per-channel dequant vs a bf16 ``jnp.dot``
      of the same shape, interleaved draws — ``kernels_int8_matmul_vs_bf16``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops.pallas import kernel_unit, select_impl

    on_tpu = jax.default_backend() == "tpu"
    B, H, D = 1, 4, 64
    T = 512 if quick else 2048
    steps = 3 if quick else 10
    reps = 2 if quick else 3
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, T, H, D), dt)
    k = jax.random.normal(kk, (B, T, H, D), dt)
    v = jax.random.normal(kv, (B, T, H, D), dt)

    attn_fn, attn_impl = select_impl("flash_attention")

    def flash_loss(q, k, v):
        o = attn_fn(q, k, v, causal=True)
        return (o.astype(jnp.float32) ** 2).sum()

    def naive_loss(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * (D ** -0.5)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        return (o ** 2).sum()

    flash_step = kernel_unit("bench_flash_fwd_bwd",
                             jax.value_and_grad(flash_loss, (0, 1, 2)))
    naive_step = jax.jit(jax.value_and_grad(naive_loss, (0, 1, 2)))

    def tput(fn):
        jax.block_until_ready(fn(q, k, v))      # compile outside timing
        best = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(q, k, v)
            jax.block_until_ready(out)
            best = max(best, B * T * steps / (time.perf_counter() - t0))
        return best

    flash_tps, naive_tps = tput(flash_step), tput(naive_step)
    out = {
        "kernels_flash_impl": attn_impl,
        "kernels_flash_fwd_bwd_tokens_per_sec": round(flash_tps, 1),
        "kernels_naive_fwd_bwd_tokens_per_sec": round(naive_tps, 1),
        "kernels_flash_vs_naive": round(flash_tps / naive_tps, 4),
    }

    # -- int8 fused dequant vs bf16 dot, interleaved (drift-immune) --
    M = N = K = 512 if quick else 2048
    rng = np.random.RandomState(0)
    a8 = jnp.asarray(rng.randint(-127, 128, (M, K)), jnp.int8)
    w8 = jnp.asarray(rng.randint(-127, 128, (N, K)), jnp.int8)
    sa = jnp.float32(0.05)
    sw = jnp.asarray(rng.rand(N).astype(np.float32) * 0.1 + 0.01)
    int8_fn, int8_impl = select_impl("int8_matmul")
    int8_step = kernel_unit(
        "bench_int8_matmul",
        lambda a, b, s_a, s_b: int8_fn(a, b, s_a, s_b))
    bdt = jnp.bfloat16 if on_tpu else jnp.float32
    a16 = (a8.astype(jnp.float32) * sa).astype(bdt)
    w16 = (w8.astype(jnp.float32) * sw[:, None]).astype(bdt)
    bf16_step = jax.jit(lambda a, b: jnp.dot(
        a, b.T, preferred_element_type=jnp.float32))

    jax.block_until_ready(int8_step(a8, w8, sa, sw))
    jax.block_until_ready(bf16_step(a16, w16))
    best_i = best_b = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            o = int8_step(a8, w8, sa, sw)
        jax.block_until_ready(o)
        best_i = max(best_i, steps / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        for _ in range(steps):
            o = bf16_step(a16, w16)
        jax.block_until_ready(o)
        best_b = max(best_b, steps / (time.perf_counter() - t0))
    gflop = 2.0 * M * N * K / 1e9
    out.update({
        "kernels_int8_impl": int8_impl,
        "kernels_int8_matmul_gflops_per_sec": round(best_i * gflop, 1),
        "kernels_bf16_matmul_gflops_per_sec": round(best_b * gflop, 1),
        "kernels_int8_matmul_vs_bf16": round(best_i / best_b, 4),
    })
    return out


def fleet_bench(quick=False):
    """Fleet-layer leg (docs/SHARDED_SERVING.md): a pjit-sharded
    ModelServer (tp=2 mesh slices) under a :class:`FleetSupervisor`.
    Reports ``fleet_scaleup_ms`` — wall time from burst onset to the
    autoscaled second replica entering rotation (the elasticity number
    the fleet layer is accepted on) — and the steady-state shed rate at
    2x admission capacity AFTER the scale-up, which the extra replica
    should hold well below the single-replica burst's."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import serving
    from mxnet_tpu.fleet import FleetSupervisor

    rng = np.random.RandomState(0)
    d_in = 64
    data = mx.sym.var("data")
    w1, b1 = mx.sym.var("fc1_weight"), mx.sym.var("fc1_bias")
    sym = mx.sym.FullyConnected(data, w1, b1, num_hidden=8, name="fc1")
    params = {
        "arg:fc1_weight": mx.nd.array(
            (rng.rand(8, d_in) * 0.1).astype(np.float32)),
        "arg:fc1_bias": mx.nd.zeros((8,)),
    }
    rules = [("fc1_weight", ("tp", None))]
    max_queue = 16
    xs = [rng.rand(1, d_in).astype(np.float32) for _ in range(16)]

    out = {}
    srv = serving.ModelServer(sym, dict(params),
                              input_shapes={"data": (1, d_in)},
                              mesh_axes={"tp": 2}, rules=rules,
                              max_queue=max_queue, max_batch=8,
                              max_wait_ms=0, deadline_ms=30_000)
    sup = FleetSupervisor(srv, service="bench", heartbeat_s=0.05,
                          interval_s=0.05, min_replicas=1,
                          max_replicas=2, shed_up=0.02,
                          idle_down_s=60, cooldown_s=0.2,
                          breach_ticks=2)
    try:
        for x in xs:
            srv.submit({"data": x})              # settle caches
        out["fleet_replica_devices"] = \
            srv.snapshot()["replicas"][0]["devices"]

        # -- burst -> scale-up latency --
        futs = []
        t0 = time.perf_counter()
        deadline = t0 + (60 if quick else 120)
        while time.perf_counter() < deadline and \
                srv.num_active_replicas() < 2:
            for i in range(2 * max_queue):
                try:
                    futs.append(srv.submit_async(
                        {"data": xs[i % len(xs)]}, deadline_ms=30_000))
                except serving.Overloaded:
                    pass
        out["fleet_scaleup_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1)
        for f in futs:
            try:
                f.result(timeout=60)
            except serving.ServingError:
                pass

        # -- steady-state shed rate at 2x capacity, scaled fleet --
        n_waves = 10 if quick else 40
        offered = shed = 0
        futs = []
        for _ in range(n_waves):
            for i in range(2 * max_queue):
                offered += 1
                try:
                    futs.append(srv.submit_async(
                        {"data": xs[i % len(xs)]}, deadline_ms=30_000))
                except serving.Overloaded:
                    shed += 1
            time.sleep(0.01)
        for f in futs:
            try:
                f.result(timeout=60)
            except serving.ServingError:
                pass
        out["fleet_shed_rate_2x"] = round(shed / max(offered, 1), 4)
        out["fleet_replicas_final"] = srv.num_active_replicas()
        out["fleet_scale_ups"] = sup.scale_ups
    finally:
        sup.stop()
        sup.registry.close()
        srv.drain(timeout=30)
    return out


def gateway_bench(quick=False):
    """Cross-process fleet leg (docs/SHARDED_SERVING.md "Deployment"):
    2 spawned fleet workers behind the HTTP gateway.  Reports the
    routing overhead ``gateway_route_p99_ms`` — the ``gateway.route_ms``
    histogram p99 (admission -> request handed to a worker: pick +
    idempotency stamp + connect; lower-better under the tripwire), with
    the end-to-end ``gateway_p99_ms`` vs ``gateway_direct_p99_ms``
    (direct ``ModelServer.submit``) pair alongside — and
    ``gateway_kill_goodput_vs_baseline``: ok-fraction of a concurrent
    burst with one worker SIGKILLed mid-burst over the ok-fraction of
    the same burst undisturbed (the mid-stream failover number)."""
    import http.client
    import threading

    import numpy as np

    from mxnet_tpu import serving
    from mxnet_tpu.fleet import ServiceRegistry, WorkerSupervisor
    from mxnet_tpu.fleet_worker import demo_model
    from mxnet_tpu.gateway import Gateway

    def pctl(lat_s, q):
        return round(float(np.percentile(np.asarray(lat_s), q)) * 1e3, 3)

    def post(addr, obj, timeout=60):
        host, _, port = addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=timeout)
        try:
            conn.request("POST", "/v1/predict",
                         body=json.dumps(obj).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            return resp.status
        finally:
            conn.close()

    n_req = 50 if quick else 200
    burst = 24 if quick else 64
    x = {"inputs": {"data": [[1.0, 2.0, 3.0, 4.0]]}}
    out = {}

    # -- direct ModelServer baseline (same model the workers build) --
    direct = demo_model()
    try:
        arr = np.asarray(x["inputs"]["data"], np.float32)
        for _ in range(8):
            direct.submit({"data": arr})             # warm
        lat = []
        for _ in range(n_req):
            t0 = time.perf_counter()
            direct.submit({"data": arr})
            lat.append(time.perf_counter() - t0)
        direct_p99 = pctl(lat, 99)
    finally:
        direct.drain(timeout=30)

    # -- 2 spawned workers behind the gateway --
    here = os.path.dirname(os.path.abspath(__file__))
    env = {**os.environ,
           "PYTHONPATH": here + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    reg = ServiceRegistry(service="bench-gw", ttl_s=1.0)
    sup = WorkerSupervisor(
        {rid: [sys.executable, "-m", "mxnet_tpu.fleet_worker",
               "--registry", reg.addr, "--service", "bench-gw",
               "--rid", rid, "--heartbeat-s", "0.1"]
         for rid in ("w0", "w1")},
        registry=reg, max_restarts=3, backoff=0.05, poll_s=0.05,
        env=env)
    gw = Gateway(registry=reg, refresh_s=0.1, suspect_s=0.5, retries=2)
    try:
        sup.wait_registered(2, timeout=180)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and (
                gw._view is None or len(gw._view.replicas) < 2):
            time.sleep(0.05)
        for _ in range(8):
            post(gw.addr, x)                         # warm both paths
        from mxnet_tpu import telemetry

        route_ms = telemetry.registry().histogram("gateway.route_ms")
        base_count = route_ms.snapshot()["count"]
        lat = []
        for _ in range(n_req):
            t0 = time.perf_counter()
            post(gw.addr, x)
            lat.append(time.perf_counter() - t0)
        out["gateway_p99_ms"] = pctl(lat, 99)
        out["gateway_direct_p99_ms"] = direct_p99
        hs = route_ms.snapshot()
        # both end-to-end p99s are dominated by the worker's batching
        # timer; the route histogram isolates the gateway's own overhead
        if hs["count"] > base_count and hs["p99"] is not None:
            out["gateway_route_p99_ms"] = round(hs["p99"], 3)

        def run_burst(kill_at=None):
            ok = [0]
            lock = threading.Lock()

            def one():
                try:
                    if post(gw.addr, x, timeout=90) == 200:
                        with lock:
                            ok[0] += 1
                except OSError:
                    pass
                except Exception:
                    pass
            ts = [threading.Thread(target=one) for _ in range(burst)]
            for i, t in enumerate(ts):
                t.start()
                if kill_at is not None and i == kill_at:
                    sup.kill_worker()
            for t in ts:
                t.join(timeout=120)
            return ok[0]

        ok_base = run_burst()
        ok_kill = run_burst(kill_at=burst // 4)
        out["gateway_burst_ok_baseline"] = ok_base
        out["gateway_burst_ok_killed"] = ok_kill
        out["gateway_retries"] = gw.retried
        out["gateway_worker_restarts"] = sup.restarts
    finally:
        gw.stop()
        sup.stop(timeout=20.0)
        reg.close()

    # -- durable generation streams: the kill-goodput number ------------
    # ``gateway_kill_goodput_vs_baseline`` is measured on *generation
    # streams*, where the failover win actually lives: a stream whose
    # worker is SIGKILLed mid-decode and resumes on the sibling counts
    # as goodput ("ok" terminal), a ``ReplicaLost`` terminal as loss.
    # (The old metric measured idempotent /v1/predict retries, which
    # masked mid-decode stream deaths entirely.)
    out.update(_gateway_gen_kill_goodput(quick=quick, env=env))
    return out


def _gateway_gen_kill_goodput(quick, env):
    """Streamed-generation kill burst behind the gateway: 2 generation
    workers, SIGKILL one after >= 1 token has streamed, count terminal
    outcomes (docs/SHARDED_SERVING.md "Failure matrix")."""
    import http.client
    import threading

    from mxnet_tpu.fleet import ServiceRegistry, WorkerSupervisor
    from mxnet_tpu.gateway import Gateway

    n_streams = 4 if quick else 8
    max_new = 8 if quick else 12
    out = {}

    reg = ServiceRegistry(service="bench-gw-gen", ttl_s=2.0)
    sup = WorkerSupervisor(
        {rid: [sys.executable, "-m", "mxnet_tpu.fleet_worker",
               "--registry", reg.addr, "--service", "bench-gw-gen",
               "--rid", rid, "--heartbeat-s", "0.1",
               "--builder", "mxnet_tpu.fleet_worker:demo_generation"]
         for rid in ("g0", "g1")},
        registry=reg, service="bench-gw-gen", max_restarts=3,
        backoff=0.05, poll_s=0.05, env=env)
    gw = Gateway(registry=reg, service="bench-gw-gen", refresh_s=0.1,
                 suspect_s=0.5, retries=2)

    def stream(i, outcomes, lock):
        host, _, port = gw.addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=120)
        try:
            conn.request(
                "POST", "/v1/generate",
                body=json.dumps({"prompt": [1 + i, 2, 3],
                                 "max_new_tokens": max_new,
                                 "deadline_ms": 60000}).encode(),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            outcome = "UNTYPED:HTTP%d" % resp.status
            if resp.status == 200:
                outcome = "UNTYPED:TruncatedStream"
                while True:
                    raw = resp.readline()
                    if not raw:
                        break
                    line = json.loads(raw)
                    if "done" in line:
                        outcome = "ok"
                        break
                    if "error" in line:
                        outcome = line["error"]
                        break
        except OSError as e:
            outcome = "UNTYPED:%s" % type(e).__name__
        finally:
            conn.close()
        with lock:
            outcomes.append(outcome)

    def run_burst(kill=False):
        outcomes, lock = [], threading.Lock()
        ts = [threading.Thread(target=stream, args=(i, outcomes, lock))
              for i in range(n_streams)]
        base_tokens = gw.tokens_streamed
        for t in ts:
            t.start()
        if kill:
            # mid-decode by construction: wait for >= 1 streamed token
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline \
                    and gw.tokens_streamed <= base_tokens:
                time.sleep(0.005)
            sup.kill_worker()
        for t in ts:
            t.join(timeout=180)
        return outcomes

    try:
        sup.wait_registered(2, timeout=180)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and (
                gw._view is None or len(gw._view.replicas) < 2):
            time.sleep(0.05)
        base = run_burst()
        killed = run_burst(kill=True)
        ok_base = sum(1 for o in base if o == "ok")
        ok_kill = sum(1 for o in killed if o == "ok")
        out["gateway_gen_ok_baseline"] = ok_base
        out["gateway_gen_ok_killed"] = ok_kill
        out["gateway_gen_replica_lost"] = sum(
            1 for o in killed if o == "ReplicaLost")
        out["gateway_streams_resumed"] = gw.streams_resumed
        out["gateway_kill_goodput_vs_baseline"] = round(
            ok_kill / max(ok_base, 1), 4)
    finally:
        gw.stop()
        sup.stop(timeout=20.0)
        reg.close()
    return out


def int8_bench(batch=128, steps=30, bf16_img_s=None):
    """INT8 resnet50 inference leg (VERDICT r3 next #8): post-training
    symmetric quantization (naive calib), run through the quantized
    symbol graph — int8 x int8 -> int32 MXU matmuls/convs
    (``ops/quantization.py``, preferred_element_type) — measured with
    the same compiled-loop discipline as the bf16 number."""
    import os as _os

    from mxnet_tpu.benchmark import compiled_throughput

    model_name = _os.environ.get("BENCH_INT8_MODEL", "resnet50_v1")
    size = int(_os.environ.get("BENCH_INT8_SIZE", "224"))
    n_calib = int(_os.environ.get("BENCH_INT8_CALIB", "32"))
    # fold conv+BN and fuse int8 chains (requantize + quantized relu /
    # pool) — the best int8 configuration measured in docs/PERF_INT8.md;
    # BENCH_INT8_FUSE=0 measures the reference-shaped per-layer graph
    fuse = _os.environ.get("BENCH_INT8_FUSE", "1") != "0"

    qnet, x32 = _build_int8_net(model_name, batch=batch, size=size,
                                n_calib=n_calib, fuse=fuse)
    r = compiled_throughput(qnet, x32, steps=steps, draws=5)
    out = {
        "int8_img_per_sec": round(r["median"], 2),
        "int8_img_per_sec_spread": [round(r["min"], 2),
                                    round(r["max"], 2)],
    }
    if bf16_img_s:
        out["int8_vs_bf16"] = round(r["median"] / bf16_img_s, 4)
    # VGG16: the weight-streaming-bound model where int8's halved bytes
    # pay off hardest (docs/PERF_INT8.md r5) — interleaved bf16/int8
    # draws in THIS process so the ratio is immune to session drift
    if _os.environ.get("BENCH_INT8_VGG", "1") != "0":
        try:
            out.update(_int8_vs_bf16_pair("vgg16", batch=batch,
                                          steps=20, reps=3, fuse=fuse))
        except Exception as e:
            out["int8_vgg16_error"] = "%s: %s" % (type(e).__name__, e)
    return out


def _build_int8_net(model_name, batch=128, size=224, n_calib=16,
                    fuse=True):
    """fp32 zoo model -> calibrated int8 SymbolBlock (+ its input).
    Shared by the int8 leg and the interleaved A/B pair."""
    import tempfile

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.contrib.quantization import quantize_model
    from mxnet_tpu.gluon import SymbolBlock
    from mxnet_tpu.gluon.model_zoo import vision

    rng = np.random.RandomState(0)
    net = getattr(vision, model_name)(classes=1000)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x32 = mx.nd.array(rng.rand(batch, 3, size, size).astype(np.float32))
    with mx.autograd.pause():
        net(x32[0:1])  # deferred init only; skip the full-batch compile
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "m")
        net.export(prefix, 0)
        sym, args, auxs = mx.model.load_checkpoint(prefix, 0)
        calib = mx.io.NDArrayIter(
            rng.rand(n_calib, 3, size, size).astype(np.float32),
            np.zeros((n_calib,)), max(1, n_calib // 2))
        qsym, qargs, qauxs = quantize_model(
            sym, args, auxs, calib_mode="naive", calib_data=calib,
            num_calib_examples=n_calib, fold_bn=fuse, fuse_int8=fuse)
        mx.model.save_checkpoint(os.path.join(d, "q"), 0, qsym, qargs,
                                 qauxs)
        qnet = SymbolBlock.imports(os.path.join(d, "q-symbol.json"),
                                   ["data"],
                                   os.path.join(d, "q-0000.params"))
    return qnet, x32


def _int8_vs_bf16_pair(model_name, batch=128, size=224, steps=20,
                       reps=3, n_calib=16, fuse=True):
    """Interleaved same-process bf16 vs int8 measurement of one model:
    each loop compiles ONCE, timed draws alternate (drift-immune)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.benchmark import interleaved_throughput
    from mxnet_tpu.gluon.model_zoo import vision

    rng = np.random.RandomState(0)
    net16 = getattr(vision, model_name)(classes=1000)
    net16.initialize(mx.init.Xavier())
    net16.cast("bfloat16")
    net16.hybridize()
    x16 = mx.nd.array(rng.rand(batch, 3, size, size)
                      .astype(np.float32)).astype("bfloat16")
    with mx.autograd.pause():
        net16(x16[0:1])
    qnet, x32 = _build_int8_net(model_name, batch=batch, size=size,
                                n_calib=n_calib, fuse=fuse)
    mb, mi = interleaved_throughput([(net16, x16), (qnet, x32)],
                                    steps=steps, reps=reps)
    key = "int8_%s" % model_name
    return {key + "_img_per_sec": round(mi, 2),
            key + "_bf16_img_per_sec": round(mb, 2),
            key + "_vs_bf16": round(mi / mb, 4)}


def long_context_bench(seq=8192, steps=5):
    """Long-context metric: full training steps at 8k/16k/32k sequences
    on one chip (flash attention keeps memory O(seq); the reference's
    long-sequence story tops out at BucketingModule — this is net-new
    capability, SURVEY §5).  Multi-chip sequence scaling (ring
    attention over an "sp" mesh axis) is exercised by dryrun_multichip.

    MFU accounting (VERDICT r4 #7, same discipline as the transformer
    number): model FLOPs per token = 6*N (matmuls, fwd+bwd) plus the
    attention score/value FLOPs 6*L*T*d (12*L*T*d for full attention,
    halved because the kernel is causal), over the v5e bf16 197-TFLOPs
    peak.  Remat recompute is NOT credited — MFU counts the math the
    model requires, so the remat config pays its recompute as lost
    utilization, which is the honest reading.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.models import TransformerLM, TransformerConfig
    from mxnet_tpu.models.transformer import make_train_step

    seqs = [int(s) for s in os.environ.get(
        "BENCH_LONGCTX_SEQS", "8192,16384,32768").split(",")]
    out = {}
    scaling = {}
    for T in seqs:
        cfg = TransformerConfig(vocab_size=32000, d_model=1024,
                                n_heads=16, n_layers=4, d_ff=4096,
                                max_len=T, dtype="bfloat16", remat=True)
        model = TransformerLM(cfg)
        params = model.init(jax.random.PRNGKey(0))
        velocity = jax.tree_util.tree_map(jnp.zeros_like, params)
        step = jax.jit(make_train_step(model))
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, T + 1), 0,
                                  cfg.vocab_size)
        x, y = toks[:, :-1], toks[:, 1:]
        params, velocity, loss = step(params, velocity, x, y)
        float(loss)
        n_steps = steps if T <= seq else max(2, steps // 2)
        best = 0.0
        for _ in range(2):
            t0 = _time.perf_counter()
            for _ in range(n_steps):
                params, velocity, loss = step(params, velocity, x, y)
            float(np.asarray(loss))  # host fetch: real barrier
            best = max(best, T * n_steps / (_time.perf_counter() - t0))
        n_params = sum(int(np.prod(v.shape))
                       for v in jax.tree_util.tree_leaves(params))
        flops_per_tok = 6 * n_params + 6 * cfg.n_layers * T * cfg.d_model
        mfu = best * flops_per_tok / 197e12
        scaling[str(T)] = {"tokens_per_sec": round(best, 1),
                           "mfu": round(mfu, 4)}
        # headline keys track the canonical seq, or the first measured
        # one if the env override dropped it (never silently absent)
        if T == seq or (seq not in seqs and T == seqs[0]):
            out["longctx_seq%d_tokens_per_sec" % T] = round(best, 1)
            out["longctx_mfu"] = round(mfu, 4)
        del params, velocity, step, model
    out["longctx_scaling"] = scaling
    return out


def transformer_bench(batch=8, seq=1024, steps=10, quick=False):
    """Secondary metric: flagship TransformerLM training throughput.

    The matmul-dominated flagship shows the MXU utilization the
    framework reaches when the workload maps cleanly onto the systolic
    array (GPT-style LM, bf16, single chip); reported as tokens/sec +
    two MFU readings: ``mfu`` from XLA's own cost analysis of the
    compiled step (``lower().cost_analysis()`` — counts the FLOPs the
    executable actually schedules) and the analytic 6*N*tokens estimate
    (``transformer_mfu_vs_v5e_peak``, kept for trajectory continuity
    with earlier rounds).  ``quick`` shrinks the model/seq so the leg
    fits a CPU-oracle budget while still exercising the cost path.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.config import config
    from mxnet_tpu.models import TransformerLM, TransformerConfig
    from mxnet_tpu.models.transformer import make_train_step

    if quick:
        batch, seq, steps = 2, min(seq, 128), 3
        cfg = TransformerConfig(vocab_size=2048, d_model=256, n_heads=4,
                                n_layers=2, d_ff=1024, max_len=seq,
                                dtype="float32", remat=False)
    else:
        # wide-and-shallow at batch 8 keeps all activations resident (no
        # remat recompute) and the d=2048 matmuls fill the MXU: measured
        # ~47% single-chip MFU vs ~19% for the d=1024/8-layer remat config
        cfg = TransformerConfig(vocab_size=32000, d_model=2048, n_heads=16,
                                n_layers=4, d_ff=8192, max_len=seq,
                                dtype="bfloat16", remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    velocity = jax.tree_util.tree_map(jnp.zeros_like, params)
    step = jax.jit(make_train_step(model))

    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (batch, seq + 1), 0, cfg.vocab_size)
    x, y = tokens[:, :-1], tokens[:, 1:]

    # cost analysis BEFORE the first call: the lowering it produces is
    # exactly the trace the compile below reuses, so the probe is ~free
    flops_per_step = None
    try:
        ca = step.lower(params, velocity, x, y).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            f = float(ca.get("flops", 0.0) or 0.0)
            if f > 0:
                flops_per_step = f
    except Exception as e:
        from mxnet_tpu import dispatch as _dispatch

        _dispatch.note_cost_failure("bench.transformer_step",
                                    "lower.cost_analysis", e)

    params, velocity, loss = step(params, velocity, x, y)  # compile
    float(loss)  # real sync
    best = 0.0
    for _ in range(2 if quick else 3):
        t0 = _time.perf_counter()
        for _ in range(steps):
            params, velocity, loss = step(params, velocity, x, y)
        float(np.asarray(loss))  # host fetch: real execution barrier
        dt = _time.perf_counter() - t0
        best = max(best, batch * seq * steps / dt)

    n_params = sum(int(np.prod(v.shape))
                   for v in jax.tree_util.tree_leaves(params))
    peak = float(config.telemetry_peak_flops)
    analytic_mfu = best * 6 * n_params / peak
    steps_per_sec = best / (batch * seq)
    out = {
        "transformer_train_tokens_per_sec": round(best, 1),
        "transformer_params_m": round(n_params / 1e6, 1),
        "transformer_mfu_vs_v5e_peak": round(analytic_mfu, 4),
        "transformer_loss": float(np.asarray(loss, np.float32)),
    }
    if flops_per_step is not None:
        out["mfu"] = round(steps_per_sec * flops_per_step / peak, 4)
        out["mfu_source"] = "xla_cost_analysis"
        out["transformer_flops_per_step"] = flops_per_step
    else:
        out["mfu"] = round(analytic_mfu, 4)
        out["mfu_source"] = "analytic_6n"
        # why the xla_cost_analysis source fell back (first recorded
        # cost-capture failure in this process, if any)
        from mxnet_tpu import dispatch as _dispatch

        fail = _dispatch.first_cost_failure()
        if fail:
            out["mfu_fallback_reason"] = "%s (%s)" % (fail["error"],
                                                      fail["stage"])
    if not quick:
        try:
            out["transformer_kernel_breakdown_ms"] = _kernel_breakdown(
                step, (params, velocity), (x, y), steps=3)
        except Exception as e:  # diagnostics must not sink the bench
            out["transformer_kernel_breakdown_error"] = str(e)
    return out


def _kernel_breakdown(step, state, data, steps=3):
    """Per-HLO-category device ms/step from a short jax.profiler trace
    (VERDICT r2 next #6 'publish a per-kernel breakdown in BENCH
    extras').  State threads through the loop — identical launches can
    be deduped by the remote runtime (same rule as the timed loops)."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from mxnet_tpu.profiler import hlo_category_breakdown

    outdir = tempfile.mkdtemp(prefix="benchprof")
    try:
        with jax.profiler.trace(outdir):
            params, velocity = state
            for _ in range(steps):
                params, velocity, loss = step(params, velocity, *data)
            float(np.asarray(loss))
        cats = hlo_category_breakdown(outdir, steps=steps)
    finally:
        shutil.rmtree(outdir, ignore_errors=True)
    return {cat: round(d["ms_per_step"], 3)
            for cat, d in sorted(cats.items(),
                                 key=lambda kv: -kv[1]["ms_per_step"])
            if d["ms_per_step"] >= 0.01}


if __name__ == "__main__":
    import atexit
    import signal as _signal

    try:
        _signal.signal(_signal.SIGTERM, _term_handler)
    except (ValueError, OSError, AttributeError):
        pass
    atexit.register(_emit_summary)
    # global ceiling until the first leg arms its own budget; legs re-arm
    # the remaining global budget on exit, so imports and between-leg
    # glue stay covered too
    _arm(_budget_s())
    try:
        main()
    except BudgetExceeded as e:
        RESULT["extra"]["budget_exceeded"] = str(e)
    except Exception as e:  # the driver needs a JSON line no matter what
        RESULT["error"] = "%s: %s" % (type(e).__name__, e)
    finally:
        _arm(0)
        _emit_summary()
        check = (RESULT["extra"].get("regression_check") or {})
        strict = os.environ.get("BENCH_REGRESSION_STRICT", "") not in (
            "", "0")
        sys.exit(3 if strict and check.get("flagged") else 0)
