"""Benchmark: ResNet-50 training throughput (images/sec) on one TPU chip.

Matches the reference's headline number: ResNet-50 training, batch 128, on
V100 = 363.69 img/s (`docs/faq/perf.md:236`, see BASELINE.md) measured via
`example/image-classification/train_imagenet.py`.  This script runs the same
workload through the Gluon user path — hybridized model-zoo ResNet-50,
SoftmaxCrossEntropyLoss, Trainer(sgd+momentum) — on synthetic ImageNet-shaped
data, and prints ONE JSON line.

Env knobs: BENCH_BATCH (default 128), BENCH_STEPS (default 20),
BENCH_MODEL (default resnet50_v1).
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_S = 363.69  # V100 fp32 batch 128, docs/faq/perf.md:236


def main():
    import numpy as np
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    model_name = os.environ.get("BENCH_MODEL", "resnet50_v1")

    platform = jax.default_backend()
    ctx = mx.tpu() if platform not in ("cpu",) else mx.cpu()

    net = getattr(vision, model_name)(classes=1000)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize(static_alloc=True, static_shape=True)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(batch, 3, 224, 224).astype(np.float32), ctx=ctx)
    y = mx.nd.array(rng.randint(0, 1000, (batch,)), ctx=ctx)

    def one_step():
        with mx.autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(batch)
        return loss

    # warmup: compile fwd+bwd+update
    for _ in range(3):
        loss = one_step()
    loss.wait_to_read()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = one_step()
    loss.wait_to_read()
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec_b%d_%s" % (batch, platform),
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # the driver needs a JSON line no matter what
        print(json.dumps({
            "metric": "resnet50_train_img_per_sec",
            "value": 0.0,
            "unit": "images/sec",
            "vs_baseline": 0.0,
            "error": "%s: %s" % (type(e).__name__, e),
        }))
        sys.exit(0)
