// Native RecordIO reader/writer (reference: dmlc-core's recordio
// implementation used by src/io/ — SURVEY.md layer 0).  Bit-identical
// framing with mxnet_tpu/recordio.py: magic 0xced7230a, uint32 whose top 3
// bits are the continuation flag and low 29 bits the payload length,
// payloads containing the magic at 4-byte-aligned offsets split into
// continuation parts (1=begin, 2=middle, 3=end; the reader re-inserts the
// magic), 4-byte record alignment.
//
// Exposed as a flat C ABI loaded via ctypes (mxnet_tpu/recordio.py picks
// it up when built; pure-python fallback otherwise).  Build: `make -C
// native` -> libmxtpu_recordio.so.
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

thread_local std::string g_error;

struct RioFile {
  FILE* fp = nullptr;
  bool writable = false;
};

int fail(const std::string& msg) {
  g_error = msg;
  return -1;
}

bool write_chunk(RioFile* f, uint32_t cflag, const char* data, size_t len) {
  uint32_t lrec = (cflag << 29) | static_cast<uint32_t>(len);
  if (std::fwrite(&kMagic, 4, 1, f->fp) != 1) return false;
  if (std::fwrite(&lrec, 4, 1, f->fp) != 1) return false;
  if (len && std::fwrite(data, 1, len, f->fp) != len) return false;
  size_t pad = (4 - len % 4) % 4;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, f->fp) != pad) return false;
  return true;
}

}  // namespace

extern "C" {

const char* rio_last_error() { return g_error.c_str(); }

void* rio_open(const char* path, int writable) {
  FILE* fp = std::fopen(path, writable ? "wb" : "rb");
  if (!fp) {
    g_error = std::string("cannot open ") + path;
    return nullptr;
  }
  auto* f = new RioFile();
  f->fp = fp;
  f->writable = writable != 0;
  return f;
}

int rio_close(void* h) {
  auto* f = static_cast<RioFile*>(h);
  if (f) {
    if (f->fp) std::fclose(f->fp);
    delete f;
  }
  return 0;
}

int64_t rio_tell(void* h) {
  return std::ftell(static_cast<RioFile*>(h)->fp);
}

int rio_seek(void* h, int64_t pos) {
  return std::fseek(static_cast<RioFile*>(h)->fp, pos, SEEK_SET) == 0
             ? 0
             : fail("seek failed");
}

int rio_write(void* h, const char* buf, uint64_t len) {
  auto* f = static_cast<RioFile*>(h);
  if (!f->writable) return fail("file not opened for writing");
  if (len > kLenMask) return fail("record too large");
  // split at 4-byte-aligned occurrences of the magic word
  std::vector<std::pair<const char*, size_t>> parts;
  size_t start = 0;
  for (size_t pos = 0; pos + 4 <= len; pos += 4) {
    uint32_t word;
    std::memcpy(&word, buf + pos, 4);
    if (word == kMagic) {
      parts.emplace_back(buf + start, pos - start);
      start = pos + 4;
      // next scan position is the following aligned word (loop += 4)
    }
  }
  parts.emplace_back(buf + start, len - start);
  bool ok;
  if (parts.size() == 1) {
    ok = write_chunk(f, 0, buf, len);
  } else {
    ok = write_chunk(f, 1, parts.front().first, parts.front().second);
    for (size_t i = 1; ok && i + 1 < parts.size(); ++i)
      ok = write_chunk(f, 2, parts[i].first, parts[i].second);
    if (ok)
      ok = write_chunk(f, 3, parts.back().first, parts.back().second);
  }
  return ok ? 0 : fail("short write");
}

// returns 0 on success, 1 at EOF, -1 on error
int rio_read(void* h, char** out, uint64_t* out_len) {
  auto* f = static_cast<RioFile*>(h);
  if (f->writable) return fail("file not opened for reading");
  std::string acc;
  bool in_multi = false;
  while (true) {
    uint32_t magic, lrec;
    size_t got = std::fread(&magic, 4, 1, f->fp);
    if (got != 1) {
      if (in_multi) return fail("truncated multi-part record at EOF");
      return 1;  // clean EOF
    }
    if (std::fread(&lrec, 4, 1, f->fp) != 1)
      return fail("truncated header");
    if (magic != kMagic) return fail("invalid RecordIO magic");
    uint32_t cflag = lrec >> 29;
    uint32_t n = lrec & kLenMask;
    std::string buf(n, '\0');
    if (n && std::fread(&buf[0], 1, n, f->fp) != n)
      return fail("truncated payload");
    size_t pad = (4 - n % 4) % 4;
    char sink[4];
    if (pad && std::fread(sink, 1, pad, f->fp) != pad)
      return fail("truncated padding");
    if (cflag == 0) {
      if (in_multi) return fail("whole record inside multi-part record");
      acc = std::move(buf);
      break;
    }
    if (cflag == 1) {
      if (in_multi) return fail("begin part inside multi-part record");
      in_multi = true;
      acc = std::move(buf);
    } else {
      if (!in_multi) return fail("continuation without a begin part");
      acc.append(reinterpret_cast<const char*>(&kMagic), 4);
      acc.append(buf);
      if (cflag == 3) break;
    }
  }
  *out_len = acc.size();
  *out = static_cast<char*>(std::malloc(acc.size() ? acc.size() : 1));
  std::memcpy(*out, acc.data(), acc.size());
  return 0;
}

void rio_free(char* buf) { std::free(buf); }

}  // extern "C"
