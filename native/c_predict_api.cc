// C predict ABI over an embedded CPython running mxnet_tpu.predict
// (reference: src/c_api/c_predict_api.cc — there the ABI fronts the C++
// GraphExecutor; here the executor IS an XLA module reached through
// Python, so the native layer embeds the interpreter and marshals
// buffers).  Thread-safe via the GIL; errors land in MXGetLastError.
#include "c_predict_api.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

struct PredictorObj {
  PyObject* predictor = nullptr;      // mxnet_tpu.predict.Predictor
  std::vector<mx_uint> shape_buf;     // backing for MXPredGetOutputShape
};

int fail(const std::string& msg) {
  g_error = msg;
  return -1;
}

int fail_py(const char* what) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = what;
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      msg += ": ";
      msg += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return fail(msg);
}

void ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // release the GIL acquired by initialization so PyGILState_Ensure
    // works from any caller thread
    PyEval_SaveThread();
  }
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

}  // namespace

extern "C" {

const char* MXGetLastError(void) { return g_error.c_str(); }

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.predict");
  if (!mod) return fail_py("import mxnet_tpu.predict failed");
  PyObject* cls = PyObject_GetAttrString(mod, "Predictor");
  Py_DECREF(mod);
  if (!cls) return fail_py("Predictor class not found");

  PyObject* shapes = PyDict_New();
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(shp, j - lo,
                       PyLong_FromUnsignedLong(input_shape_data[j]));
    PyDict_SetItemString(shapes, input_keys[i], shp);
    Py_DECREF(shp);
  }
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* json = PyUnicode_FromString(symbol_json_str);

  PyObject* ctx = nullptr;
  {
    PyObject* ctxmod = PyImport_ImportModule("mxnet_tpu.context");
    if (ctxmod) {
      const char* maker = (dev_type == 1) ? "cpu" : "tpu";
      PyObject* fn = PyObject_GetAttrString(ctxmod, maker);
      if (fn) {
        ctx = PyObject_CallFunction(fn, "i", dev_id);
        Py_DECREF(fn);
      }
      Py_DECREF(ctxmod);
    }
    if (!ctx) {
      Py_DECREF(cls);
      Py_DECREF(shapes);
      Py_DECREF(params);
      Py_DECREF(json);
      return fail_py("context creation failed");
    }
  }

  PyObject* kwargs = PyDict_New();
  PyDict_SetItemString(kwargs, "ctx", ctx);
  PyDict_SetItemString(kwargs, "input_shapes", shapes);
  PyObject* args = PyTuple_Pack(2, json, params);
  PyObject* pred = PyObject_Call(cls, args, kwargs);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_DECREF(cls);
  Py_DECREF(shapes);
  Py_DECREF(params);
  Py_DECREF(json);
  Py_DECREF(ctx);
  if (!pred) return fail_py("Predictor construction failed");

  auto* obj = new PredictorObj();
  obj->predictor = pred;
  *out = obj;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const mx_float* data, mx_uint size) {
  Gil gil;
  auto* obj = static_cast<PredictorObj*>(handle);
  // shape of this input comes from the predictor's executor binding
  PyObject* np = PyImport_ImportModule("numpy");
  if (!np) return fail_py("numpy import failed");
  PyObject* frombuf = PyObject_GetAttrString(np, "frombuffer");
  PyObject* mem = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<mx_float*>(data)),
      static_cast<Py_ssize_t>(size) * 4, PyBUF_READ);
  PyObject* view =
      PyObject_CallFunction(frombuf, "Os", mem, "float32");
  Py_DECREF(frombuf);
  Py_DECREF(mem);
  Py_DECREF(np);
  if (!view) return fail_py("input buffer conversion failed");
  // copy out of the caller's buffer NOW — the reference ABI copies
  // synchronously, and the zero-copy view would alias freed memory if
  // the caller releases its buffer before forward()
  PyObject* flat = PyObject_CallMethod(view, "copy", nullptr);
  Py_DECREF(view);
  if (!flat) return fail_py("input copy failed");

  // reshape to the bound input's shape
  PyObject* exec = PyObject_GetAttrString(obj->predictor, "_executor");
  PyObject* arg_dict = exec ? PyObject_GetAttrString(exec, "arg_dict")
                            : nullptr;
  PyObject* bound =
      arg_dict ? PyMapping_GetItemString(arg_dict, key) : nullptr;
  PyObject* shape = bound ? PyObject_GetAttrString(bound, "shape")
                          : nullptr;
  Py_XDECREF(exec);
  Py_XDECREF(arg_dict);
  Py_XDECREF(bound);
  if (!shape) {
    Py_DECREF(flat);
    return fail_py("unknown input key");
  }
  PyObject* reshaped =
      PyObject_CallMethod(flat, "reshape", "O", shape);
  Py_DECREF(flat);
  Py_DECREF(shape);
  if (!reshaped) return fail_py("input reshape failed");
  PyObject* r = PyObject_CallMethod(obj->predictor, "set_input", "sO",
                                    key, reshaped);
  Py_DECREF(reshaped);
  if (!r) return fail_py("set_input failed");
  Py_DECREF(r);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Gil gil;
  auto* obj = static_cast<PredictorObj*>(handle);
  PyObject* r = PyObject_CallMethod(obj->predictor, "forward", nullptr);
  if (!r) return fail_py("forward failed");
  Py_DECREF(r);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim) {
  Gil gil;
  auto* obj = static_cast<PredictorObj*>(handle);
  // get_output_shape works before the first forward too (it infers from
  // the binding), matching the reference ABI's buffer-sizing flow
  PyObject* shape = PyObject_CallMethod(obj->predictor,
                                        "get_output_shape", "I", index);
  if (!shape) return fail_py("get_output_shape failed");
  Py_ssize_t n = PyTuple_Size(shape);
  obj->shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    obj->shape_buf[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, i)));
  Py_DECREF(shape);
  *shape_data = obj->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float* data,
                    mx_uint size) {
  Gil gil;
  auto* obj = static_cast<PredictorObj*>(handle);
  PyObject* out = PyObject_CallMethod(obj->predictor, "get_output", "I",
                                      index);
  if (!out) return fail_py("get_output failed");
  PyObject* arr = PyObject_CallMethod(out, "asnumpy", nullptr);
  Py_DECREF(out);
  if (!arr) return fail_py("asnumpy failed");
  PyObject* f32 = PyObject_CallMethod(arr, "astype", "s", "float32");
  Py_DECREF(arr);
  if (!f32) return fail_py("astype failed");
  PyObject* bytes = PyObject_CallMethod(f32, "tobytes", nullptr);
  Py_DECREF(f32);
  if (!bytes) return fail_py("tobytes failed");
  Py_ssize_t blen = PyBytes_Size(bytes);
  if (static_cast<Py_ssize_t>(size) * 4 < blen) {
    Py_DECREF(bytes);
    return fail("output buffer too small");
  }
  std::memcpy(data, PyBytes_AsString(bytes), blen);
  Py_DECREF(bytes);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Gil gil;
  auto* obj = static_cast<PredictorObj*>(handle);
  Py_XDECREF(obj->predictor);
  delete obj;
  return 0;
}

}  // extern "C"
