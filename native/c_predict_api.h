/*
 * C predict API (reference: include/mxnet/c_predict_api.h:1-283 — the
 * standalone inference ABI used by the cpp/matlab/amalgamation frontends).
 *
 * Same function surface and calling conventions; the implementation
 * (c_predict_api.cc) embeds CPython and drives mxnet_tpu.predict.Predictor,
 * whose executor is one AOT-compiled XLA module on TPU.
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;

/* Last error message for this thread (reference MXGetLastError). */
const char *MXGetLastError(void);

/* Create a predictor from a symbol JSON string + a .params blob.
 * input_keys/input_shape_indptr/input_shape_data describe the data
 * inputs exactly like the reference: shapes of input i are
 * input_shape_data[indptr[i] .. indptr[i+1]).  dev_type: 1 = cpu,
 * 2 = accelerator (tpu).  Returns 0 on success, -1 on error. */
int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out);

/* Copy data into the named input (reference MXPredSetInput). */
int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size);

/* Run the forward pass (reference MXPredForward). */
int MXPredForward(PredictorHandle handle);

/* Shape of output `index`; pointers are valid until the next call on
 * this handle (reference MXPredGetOutputShape). */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim);

/* Copy output `index` into user memory (reference MXPredGetOutput). */
int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size);

/* Free the predictor (reference MXPredFree). */
int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif
#endif /* MXTPU_C_PREDICT_API_H_ */
