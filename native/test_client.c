/* C client for the predict ABI (reference: the cpp predict examples over
 * c_predict_api.h).  Usage: test_client <symbol.json> <model.params>
 * <batch> <feature_dim>.  Loads the exported model, feeds a ramp input,
 * prints the argmax of each row's output. */
#include "c_predict_api.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static char *read_file(const char *path, long *size) {
  FILE *f = fopen(path, "rb");
  if (!f) {
    fprintf(stderr, "cannot open %s\n", path);
    exit(1);
  }
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) {
    fprintf(stderr, "short read on %s\n", path);
    exit(1);
  }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char **argv) {
  if (argc < 5) {
    fprintf(stderr,
            "usage: %s symbol.json model.params batch feature_dim\n",
            argv[0]);
    return 2;
  }
  long json_size, param_size;
  char *json = read_file(argv[1], &json_size);
  char *params = read_file(argv[2], &param_size);
  mx_uint batch = (mx_uint)atoi(argv[3]);
  mx_uint dim = (mx_uint)atoi(argv[4]);

  const char *keys[] = {"data"};
  mx_uint indptr[] = {0, 2};
  mx_uint shape[] = {batch, dim};
  PredictorHandle h = NULL;
  if (MXPredCreate(json, params, (int)param_size, 1, 0, 1, keys, indptr,
                   shape, &h) != 0) {
    fprintf(stderr, "MXPredCreate failed: %s\n", MXGetLastError());
    return 1;
  }

  mx_uint n = batch * dim;
  mx_float *input = (mx_float *)malloc(n * sizeof(mx_float));
  for (mx_uint i = 0; i < n; ++i)
    input[i] = (mx_float)(i % dim) / (mx_float)dim - 0.5f;
  if (MXPredSetInput(h, "data", input, n) != 0 || MXPredForward(h) != 0) {
    fprintf(stderr, "predict failed: %s\n", MXGetLastError());
    return 1;
  }

  mx_uint *oshape = NULL, ondim = 0;
  if (MXPredGetOutputShape(h, 0, &oshape, &ondim) != 0) {
    fprintf(stderr, "shape failed: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint osize = 1;
  printf("output shape: (");
  for (mx_uint i = 0; i < ondim; ++i) {
    osize *= oshape[i];
    printf(i ? ", %u" : "%u", oshape[i]);
  }
  printf(")\n");

  mx_float *out = (mx_float *)malloc(osize * sizeof(mx_float));
  if (MXPredGetOutput(h, 0, out, osize) != 0) {
    fprintf(stderr, "get_output failed: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint classes = oshape[ondim - 1];
  for (mx_uint b = 0; b < batch && b < 4; ++b) {
    mx_uint best = 0;
    for (mx_uint c = 1; c < classes; ++c)
      if (out[b * classes + c] > out[b * classes + best]) best = c;
    printf("row %u argmax %u\n", b, best);
  }
  MXPredFree(h);
  printf("C_PREDICT_OK\n");
  free(json);
  free(params);
  free(input);
  free(out);
  return 0;
}
