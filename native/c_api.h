/*
 * Imperative C API: NDArray handles + operator invoke by name.
 *
 * Reference: include/mxnet/c_api.h (the 203-function ABI every frontend
 * marshals through — SURVEY §2.1 "C API" row) — here scoped to the
 * imperative data plane the C++ frontend needs: NDArray lifecycle,
 * host<->device copies, shape/dtype introspection, save/load, and
 * MXImperativeInvoke against the TPU op registry.  The implementation
 * (c_api.cc) embeds CPython and drives mxnet_tpu; every op executes as
 * a cached-jitted XLA computation on the TPU.
 *
 * Conventions match the reference ABI: every call returns 0 on success
 * and -1 on error with the message available from MXGetLastError()
 * (thread-local).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef void *NDArrayHandle;

/* dtype codes (reference python/mxnet/base.py _DTYPE_NP_TO_MX):
 * 0=float32 1=float64 2=float16 3=uint8 4=int32 5=int8 6=int64;
 * TPU-native extension: 7=bfloat16. */

const char *MXGetLastError(void);

/* Create a zero-initialized NDArray.  dev_type: 1 = cpu, 2 = tpu. */
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int dtype, NDArrayHandle *out);

/* Synchronous host->device copy; size is in elements and must equal the
 * array's size.  `data` is interpreted in the array's dtype. */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size);

/* Synchronous device->host copy; size in elements and must equal the
 * array's element count (mirrors the FromCPU contract). */
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size);

/* Shape of the array; pointers valid until the next call on this handle. */
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_ndim,
                      const mx_uint **out_pdata);

int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);

int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);

/* Block until the array's async computation is complete (reference
 * WaitToRead — the sync point where deferred errors surface). */
int MXNDArrayWaitToRead(NDArrayHandle handle);

/* Block until all outstanding computation is complete. */
int MXNDArrayWaitAll(void);

int MXNDArrayFree(NDArrayHandle handle);

/* Save named arrays to the reference-compatible .params container. */
int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys);

/* Load a .params container.  Output pointers are owned by the library
 * and valid until the next MXNDArrayLoad on this thread; the handles
 * must each be freed with MXNDArrayFree. */
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);

/* Names of all registered operators.  Pointers owned by the library,
 * valid until the next call on this thread. */
int MXListAllOpNames(mx_uint *out_size, const char ***out_array);

/* Invoke a registered operator by name on NDArray inputs.  Scalar/tuple
 * hyper-parameters are passed as strings (reference convention: the
 * frontend stringifies, the backend parses against the op signature).
 * `*outputs` is set to a thread-local array of fresh handles (caller
 * frees each with MXNDArrayFree; the array itself is reused by the next
 * invoke on this thread). */
int MXImperativeInvoke(const char *op_name, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals);

/* ---------------------------------------------------------------------
 * Symbol ABI (reference src/c_api/c_api_symbolic.cc).  Graph
 * composition: atomic symbol + compose, JSON round trip, list
 * arguments/outputs/aux, shape inference.
 * ------------------------------------------------------------------ */
typedef void *SymbolHandle;

int MXSymbolCreateVariable(const char *name, SymbolHandle *out);

/* An atomic symbol holds an op + stringified hyper-params and must be
 * composed with inputs before use (MXSymbolCompose, which follows the
 * reference in updating the handle in place). */
int MXSymbolCreateAtomicSymbol(const char *op_name, mx_uint num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out);

/* Compose with inputs; keys may be NULL for positional args. */
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
/* JSON string owned by the library, valid until the next call on this
 * thread. */
int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json);

/* Name lists owned by the library, valid until the next symbol-list
 * call on this thread. */
int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                          const char ***out_array);
int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                        const char ***out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint *out_size,
                                const char ***out_array);

/* CSR-style shape exchange like the reference: arg_ind_ptr[i] indexes
 * into arg_shape_data for the i-th known arg; outputs come back in the
 * same layout (pointers valid until the next call on this thread).
 * complete is 1 when every returned shape is fully known. */
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char **keys, const mx_uint *arg_ind_ptr,
                       const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data, int *complete);

int MXSymbolFree(SymbolHandle sym);

/* ---------------------------------------------------------------------
 * Executor ABI (reference src/c_api/c_api_executor.cc).  grad_req codes
 * (OpReqType): 0 = null, 1 = write, 2 = add.
 * ------------------------------------------------------------------ */
typedef void *ExecutorHandle;

int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   mx_uint num_args, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store,
                   const mx_uint *grad_req_type, mx_uint aux_states_len,
                   NDArrayHandle *aux_states, ExecutorHandle *out);

int MXExecutorForward(ExecutorHandle ex, int is_train);

/* head gradients may be NULL/len 0 for loss heads */
int MXExecutorBackward(ExecutorHandle ex, mx_uint len,
                       NDArrayHandle *head_grads);

/* Fresh handles per call (caller frees each with MXNDArrayFree; the
 * array itself is reused by the next call on this thread). */
int MXExecutorOutputs(ExecutorHandle ex, mx_uint *out_size,
                      NDArrayHandle **out);

int MXExecutorFree(ExecutorHandle ex);

/* ---------------------------------------------------------------------
 * KVStore ABI (reference src/c_api/c_api.cc MXKVStore*).
 * ------------------------------------------------------------------ */
typedef void *KVStoreHandle;

int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreInit(KVStoreHandle kv, mx_uint num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle kv, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle kv, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStoreGetRank(KVStoreHandle kv, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle kv, int *size);
int MXKVStoreFree(KVStoreHandle kv);

/* ---------------------------------------------------------------------
 * Autograd ABI (reference src/c_api/c_api_ndarray.cc MXAutograd*):
 * imperative training without the executor — record, backward, read
 * grads.
 * ------------------------------------------------------------------ */
/* Returns the previous flag in *prev. */
int MXAutogradSetIsRecording(int is_recording, int *prev);
int MXAutogradSetIsTraining(int is_training, int *prev);

/* Attach gradient buffers to variables (grad_reqs: write). */
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle *var_handles,
                            NDArrayHandle *grad_handles);

/* Backward from outputs; head gradients may be NULL (loss heads). */
int MXAutogradBackward(mx_uint num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph);

/* Fresh handle for the gradient attached to this array (caller frees
 * with MXNDArrayFree). */
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out);

/* ---------------------------------------------------------------------
 * DataIter ABI (reference MXDataIter*): file-backed iterators created
 * by name with string params; Next() advances the cursor, Get*() read
 * the current batch.
 * ------------------------------------------------------------------ */
typedef void *DataIterHandle;

/* Names of creatable iterators (library-owned, valid until the next
 * call on this thread). */
int MXListDataIters(mx_uint *out_size, const char ***out_array);

int MXDataIterCreateIter(const char *name, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
/* *out = 1 while a batch is available. */
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
/* Fresh NDArray handles for the current batch (caller frees each). */
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);
int MXDataIterFree(DataIterHandle handle);

/* Reference-parity shutdown hook (engine teardown there; no-op here —
 * XLA teardown happens at process exit). */
int MXNotifyShutdown(void);

/* ---------------------------------------------------------------------
 * NDArray extras (reference c_api.cc): views, raw-byte serde, storage
 * type, grad state, sparse accessors.
 * ------------------------------------------------------------------ */
/* Placeholder array (deferred-alloc slot filler). */
int MXNDArrayCreateNone(NDArrayHandle *out);

/* Like MXNDArrayCreate; delay_alloc accepted for ABI parity (XLA owns
 * allocation, so it has no effect). */
int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out);

/* Fresh handle viewing rows [begin, end) / row `idx`.  Dense-backed:
 * the result is a copy, not an aliasing view (XLA arrays are
 * immutable); the reference's mutate-through-view idiom is not
 * supported through this ABI. */
int MXNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                   NDArrayHandle *out);
int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle *out);

int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int *dims,
                     NDArrayHandle *out);
int MXNDArrayReshape64(NDArrayHandle handle, int ndim,
                       const long long *dims, NDArrayHandle *out);

/* Storage type codes (reference NDArrayStorageType): 0 undefined,
 * 1 default, 2 row_sparse, 3 csr. */
int MXNDArrayGetStorageType(NDArrayHandle handle, int *out);

/* Fresh handle sharing the value but detached from the autograd tape. */
int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out);

int MXNDArraySetGradState(NDArrayHandle handle, int state);
int MXNDArrayGetGradState(NDArrayHandle handle, int *out);

/* Single-array dmlc-format serde.  Buffer owned by the library, valid
 * until the next SaveRawBytes on this thread. */
int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                          const char **out_buf);
int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                              NDArrayHandle *out);

/* Sparse accessors: values / aux (row_sparse: indices; csr: indptr,
 * indices).  Fresh handles (caller frees). */
int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle *out);
int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle *out);
int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int *out_type);

/* Invoke with output storage types (reference MXImperativeInvokeEx);
 * *out_stypes points at a thread-local array. */
int MXImperativeInvokeEx(const char *op_name, int num_inputs,
                         NDArrayHandle *inputs, int *num_outputs,
                         NDArrayHandle **outputs, int num_params,
                         const char **param_keys, const char **param_vals,
                         const int **out_stypes);

/* ---------------------------------------------------------------------
 * DLPack interop (reference MXNDArrayToDLPack/FromDLPack).  The host
 * buffer is exported/imported (kDLCPU); device memory stays owned by
 * XLA.  Struct layout is the standard DLPack 0.x ABI.
 * ------------------------------------------------------------------ */
typedef void *DLManagedTensorHandle;

int MXNDArrayToDLPack(NDArrayHandle handle, DLManagedTensorHandle *out);
int MXNDArrayFromDLPack(DLManagedTensorHandle dlpack, NDArrayHandle *out);
/* transient_handle accepted for reference signature parity. */
int MXNDArrayFromDLPackEx(DLManagedTensorHandle dlpack,
                          const int transient_handle, NDArrayHandle *out);
int MXNDArrayCallDLPackDeleter(DLManagedTensorHandle dlpack);

/* ---------------------------------------------------------------------
 * CachedOp plane (reference c_api_ndarray.cc:235, imperative/cached_op):
 * bind-once-run-many graph handle for frontend inference loops.
 * ------------------------------------------------------------------ */
typedef void *CachedOpHandle;

int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle *out);
int MXCreateCachedOpEx(SymbolHandle sym, int num_flags, const char **keys,
                       const char **vals, CachedOpHandle *out);
int MXFreeCachedOp(CachedOpHandle handle);
/* Fresh output handles (caller frees each; array reused per thread). */
int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle *inputs, int *num_outputs,
                     NDArrayHandle **outputs);
int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, const int **out_stypes);

/* ---------------------------------------------------------------------
 * KVStore extras (reference c_api.cc): custom updaters, barrier,
 * string keys, row-sparse pull, node roles, server commands.
 * ------------------------------------------------------------------ */
typedef void (*MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                 NDArrayHandle local, void *handle);
typedef void (*MXKVStoreStrUpdater)(const char *key, NDArrayHandle recv,
                                    NDArrayHandle local, void *handle);

int MXKVStoreSetUpdater(KVStoreHandle kv, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreSetUpdaterEx(KVStoreHandle kv, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void *updater_handle);

int MXKVStoreBarrier(KVStoreHandle kv);

int MXKVStoreInitEx(KVStoreHandle kv, mx_uint num, const char **keys,
                    NDArrayHandle *vals);
int MXKVStorePushEx(KVStoreHandle kv, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority);
int MXKVStorePullEx(KVStoreHandle kv, mx_uint num, const char **keys,
                    NDArrayHandle *vals, int priority);

/* Pull only the rows listed in row_ids[i] (int64 1-D arrays); vals[i]
 * receives the full-shaped table with zeros off the requested rows
 * (dense-backed row_sparse). */
int MXKVStorePullRowSparse(KVStoreHandle kv, mx_uint num, const int *keys,
                           NDArrayHandle *vals,
                           const NDArrayHandle *row_ids, int priority);

int MXKVStoreIsWorkerNode(int *ret);
int MXKVStoreIsServerNode(int *ret);
int MXKVStoreIsSchedulerNode(int *ret);

/* Reference spelling preserved (the triple-m typo is ABI).  Command 0
 * installs a server-side optimizer; its body must be a PROTOCOL-0
 * (ASCII) pickle — the reference's own convention
 * (pickle.dumps(optimizer, 0)), since binary pickles cannot cross a
 * NUL-terminated char* boundary. */
int MXKVStoreSendCommmandToServers(KVStoreHandle kv, int cmd_id,
                                   const char *cmd_body);

/* Store type string, library-owned. */
int MXKVStoreGetType(KVStoreHandle kv, const char **type);

/* ---------------------------------------------------------------------
 * RecordIO ABI (reference MXRecordIO*): the container im2rec produces.
 * ------------------------------------------------------------------ */
typedef void *RecordIOHandle;

int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOWriterFree(RecordIOHandle handle);
int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char *buf,
                                size_t size);
int MXRecordIOWriterTell(RecordIOHandle handle, size_t *pos);

int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out);
int MXRecordIOReaderFree(RecordIOHandle handle);
/* *buf -> thread-local copy of the record, *size its length; *buf NULL
 * and *size 0 at end of file. */
int MXRecordIOReaderReadRecord(RecordIOHandle handle, const char **buf,
                               size_t *size);
int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos);
int MXRecordIOReaderTell(RecordIOHandle handle, size_t *pos);

/* ---------------------------------------------------------------------
 * Profiler ABI (reference src/c_api/c_api_profile.cc).
 * ------------------------------------------------------------------ */
int MXSetProcessProfilerConfig(int num_params, const char *const *keys,
                               const char *const *vals,
                               KVStoreHandle kv_handle);
int MXSetProfilerConfig(int num_params, const char *const *keys,
                        const char *const *vals);
/* state: 0 = stop, 1 = run. */
int MXSetProcessProfilerState(int state, int profile_process,
                              KVStoreHandle kv_handle);
int MXSetProfilerState(int state);
int MXDumpProcessProfile(int finished, int profile_process,
                         KVStoreHandle kv_handle);
int MXDumpProfile(int finished);
/* Aggregate stats table; string owned by the library, valid until the
 * next call on this thread. */
int MXAggregateProfileStatsPrint(const char **out_str, int reset);
int MXProcessProfilePause(int paused, int profile_process,
                          KVStoreHandle kv_handle);
int MXProfilePause(int paused);

/* ---------------------------------------------------------------------
 * Symbol extras (reference c_api_symbolic.cc): attributes, dtype
 * inference, internals/outputs, file round trip, op introspection.
 * ------------------------------------------------------------------ */
/* dtype codes as in the NDArray plane; -1 marks unknown. */
int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                      const char **keys, const int *arg_type_data,
                      mx_uint *in_type_size, const int **in_type_data,
                      mx_uint *out_type_size, const int **out_type_data,
                      mx_uint *aux_type_size, const int **aux_type_data,
                      int *complete);

/* *success = 1 and *out -> library-owned string when the attr exists. */
int MXSymbolGetAttr(SymbolHandle sym, const char *key, const char **out,
                    int *success);
int MXSymbolSetAttr(SymbolHandle sym, const char *key, const char *value);
/* Flat [key0, val0, key1, val1, ...] listing, library-owned. */
int MXSymbolListAttr(SymbolHandle sym, mx_uint *out_size,
                     const char ***out);

int MXSymbolCopy(SymbolHandle sym, SymbolHandle *out);
int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle *out);
int MXSymbolGetOutput(SymbolHandle sym, mx_uint index, SymbolHandle *out);
int MXSymbolGetNumOutputs(SymbolHandle sym, mx_uint *out);

int MXSymbolSaveToFile(SymbolHandle sym, const char *fname);
int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);

/* Op introspection for frontend codegen (reference
 * MXSymbolListAtomicSymbolCreators/GetAtomicSymbolInfo): a creator is an
 * opaque id for one registered op. */
typedef void *AtomicSymbolCreator;

int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name);
/* Strings/arrays library-owned, valid until the next call on this
 * thread.  key_var_num_args/return_type may be empty strings. */
int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char **name, const char **description,
                                mx_uint *num_args, const char ***arg_names,
                                const char ***arg_type_infos,
                                const char ***arg_descriptions,
                                const char **key_var_num_args,
                                const char **return_type);

/* ---------------------------------------------------------------------
 * Executor monitor callback (reference graph_executor.cc:1295): while
 * installed, forward runs the graph observably and the callback fires
 * per intermediate tensor.  The NDArray handle passed to the callback
 * is owned by the library for the duration of the call.
 * ------------------------------------------------------------------ */
typedef void (*ExecutorMonitorCallback)(const char *name,
                                        NDArrayHandle arr, void *cb_handle);

int MXExecutorSetMonitorCallback(ExecutorHandle ex,
                                 ExecutorMonitorCallback callback,
                                 void *callback_handle);
int MXExecutorSetMonitorCallbackEX(ExecutorHandle ex,
                                   ExecutorMonitorCallback callback,
                                   void *callback_handle, int monitor_all);

/* ---------------------------------------------------------------------
 * Autograd extras.
 * ------------------------------------------------------------------ */
int MXAutogradIsRecording(unsigned char *curr);
int MXAutogradIsTraining(unsigned char *curr);

/* Backward with explicit variables: *grad_handles receives fresh grad
 * handles for the listed variables (thread-local array; caller frees
 * each handle), *grad_stypes their storage codes.  create_graph is not
 * supported through the ABI and must be 0. */
int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle *output_handles,
                         NDArrayHandle *ograd_handles, mx_uint num_variables,
                         NDArrayHandle *var_handles, int retain_graph,
                         int create_graph, int is_train,
                         NDArrayHandle **grad_handles,
                         const int **grad_stypes);

/* ---------------------------------------------------------------------
 * Runtime misc.
 * ------------------------------------------------------------------ */
int MXGetVersion(int *out);
int MXRandomSeed(int seed);
int MXRandomSeedContext(int seed, int dev_type, int dev_id);
/* Accelerator device count (TPU chips here; the reference counts GPUs). */
int MXGetGPUCount(int *out);

/* =====================================================================
 * Round-4 completion planes — the remainder of the reference's
 * include/mxnet/c_api.h surface.  Same conventions throughout: 0/-1
 * return, MXGetLastError, thread-local result buffers.
 * ===================================================================== */

/* ---- symbol extras (reference c_api_symbolic.cc) -------------------- */
int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out);
/* *success = 0 and *out = "" when the symbol has no single name. */
int MXSymbolGetName(SymbolHandle sym, const char **out, int *success);
/* *out = NULL when the node has no children (a leaf variable). */
int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle *out);
int MXSymbolGetInputSymbols(SymbolHandle sym, SymbolHandle **inputs,
                            int *input_size);
/* Symbolic gradient of this symbol's outputs w.r.t. the named args. */
int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt, const char **wrt,
                 SymbolHandle *out);
/* Same marshalling as MXSymbolInferShape; unknown entries come back
 * with ndim 0 instead of failing. */
int MXSymbolInferShapePartial(SymbolHandle sym, mx_uint num_args,
                              const char **keys,
                              const mx_uint *arg_ind_ptr,
                              const mx_uint *arg_shape_data,
                              mx_uint *in_shape_size,
                              const mx_uint **in_shape_ndim,
                              const mx_uint ***in_shape_data,
                              mx_uint *out_shape_size,
                              const mx_uint **out_shape_ndim,
                              const mx_uint ***out_shape_data,
                              mx_uint *aux_shape_size,
                              const mx_uint **aux_shape_ndim,
                              const mx_uint ***aux_shape_data,
                              int *complete);
/* Unknown dtypes come back as -1 instead of failing. */
int MXSymbolInferTypePartial(SymbolHandle sym, mx_uint num_args,
                             const char **keys, const int *arg_type_data,
                             mx_uint *in_type_size, const int **in_type_data,
                             mx_uint *out_type_size,
                             const int **out_type_data,
                             mx_uint *aux_type_size,
                             const int **aux_type_data, int *complete);
/* Flat [key0, val0, key1, val1, ...] of this node's own attrs. */
int MXSymbolListAttrShallow(SymbolHandle sym, mx_uint *out_size,
                            const char ***out);
int MXSymbolPrint(SymbolHandle sym, const char **out_str);
/* Control-flow subgraph extraction: this framework's control-flow ops
 * carry subgraphs explicitly, so there is never an implicit subgraph to
 * cut; always returns *input_size = 0 (the reference's answer for
 * graphs without subgraph markers). */
int MXSymbolCutSubgraph(SymbolHandle sym, SymbolHandle **inputs,
                        int *input_size);

/* ---- executor extras (reference c_api_executor.cc) ------------------ */
/* Shape-driven bind: allocates arg/grad/aux arrays.  Handle arrays are
 * thread-local (valid until the next simple-bind/reshape on this
 * thread); grad entries are NULL under grad_req 0. */
int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         mx_uint grad_req_type, mx_uint num_provided_args,
                         const char **provided_arg_shape_names,
                         const mx_uint *provided_arg_shape_ind_ptr,
                         const mx_uint *provided_arg_shape_data,
                         mx_uint *num_in_args, NDArrayHandle **in_args,
                         NDArrayHandle **arg_grads, mx_uint *num_aux_states,
                         NDArrayHandle **aux_states, ExecutorHandle *out);
/* Rebind to new shapes; the old executor stays valid (reference
 * MXExecutorReshape semantics with partial_shaping/allow_up_sizing). */
int MXExecutorReshape(int partial_shaping, int allow_up_sizing,
                      ExecutorHandle ex, mx_uint num_provided_args,
                      const char **provided_arg_shape_names,
                      const mx_uint *provided_arg_shape_ind_ptr,
                      const mx_uint *provided_arg_shape_data,
                      mx_uint *num_in_args, NDArrayHandle **in_args,
                      NDArrayHandle **arg_grads, mx_uint *num_aux_states,
                      NDArrayHandle **aux_states, ExecutorHandle *out);
int MXExecutorPrint(ExecutorHandle ex, const char **out_str);
int MXExecutorBackwardEx(ExecutorHandle ex, mx_uint len,
                         NDArrayHandle *head_grads, int is_train);
/* Bind with a group->context map; the TPU executor places group2ctx
 * groups across the context list (model parallelism). */
int MXExecutorBindX(SymbolHandle sym, int dev_type, int dev_id,
                    mx_uint num_map_keys, const char **map_keys,
                    const int *map_dev_types, const int *map_dev_ids,
                    mx_uint num_args, NDArrayHandle *in_args,
                    NDArrayHandle *arg_grad_store,
                    const mx_uint *grad_req_type, mx_uint aux_states_len,
                    NDArrayHandle *aux_states, ExecutorHandle *out);
int MXExecutorBindEX(SymbolHandle sym, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char **map_keys,
                     const int *map_dev_types, const int *map_dev_ids,
                     mx_uint num_args, NDArrayHandle *in_args,
                     NDArrayHandle *arg_grad_store,
                     const mx_uint *grad_req_type, mx_uint aux_states_len,
                     NDArrayHandle *aux_states, ExecutorHandle shared_exec,
                     ExecutorHandle *out);
/* Operator fusion happens inside XLA after tracing, so the symbol-level
 * graph IS the optimized graph this ABI can expose. */
int MXExecutorGetOptimizedSymbol(ExecutorHandle ex, SymbolHandle *out);

/* ---- KVStore extras ------------------------------------------------- */
typedef void (*MXKVStoreServerController)(int head, const char *body,
                                          void *controller_handle);

int MXKVStorePullRowSparseEx(KVStoreHandle kv, mx_uint num,
                             const char **keys, NDArrayHandle *vals,
                             const NDArrayHandle *row_ids, int priority);
int MXKVStorePullWithSparse(KVStoreHandle kv, mx_uint num, const int *keys,
                            NDArrayHandle *vals, int priority,
                            unsigned char ignore_sparse);
int MXKVStorePullWithSparseEx(KVStoreHandle kv, mx_uint num,
                              const char **keys, NDArrayHandle *vals,
                              int priority, unsigned char ignore_sparse);
int MXKVStoreSetGradientCompression(KVStoreHandle kv, mx_uint num_params,
                                    const char **keys, const char **vals);
/* Blocks a dist server role in the reference; the dist_async host
 * parameter server here runs in-process, so this validates the kvstore
 * type and returns (an error for local stores). */
int MXKVStoreRunServer(KVStoreHandle kv, MXKVStoreServerController controller,
                       void *controller_handle);
int MXKVStoreSetBarrierBeforeExit(KVStoreHandle kv, int do_barrier);
/* Node liveness lives in elastic.py's Watchdog; the kvstore layer never
 * declares nodes dead, so the count is always 0. */
int MXKVStoreGetNumDeadNode(KVStoreHandle kv, int node_id, int *number);
/* Seeds coordinator environment variables (reference ps-lite env). */
int MXInitPSEnv(mx_uint num_vars, const char **keys, const char **vals);

/* ---- NDArray extras ------------------------------------------------- */
/* Host pointer to the array's data: syncs device->host into a buffer
 * owned by the handle, valid until the next call on the same handle.
 * Writes through the pointer do NOT propagate back to the device. */
int MXNDArrayGetData(NDArrayHandle handle, void **out_pdata);
/* XLA buffers are immutable; readable == writable, so this is
 * WaitToRead (kept for ABI parity). */
int MXNDArrayWaitToWrite(NDArrayHandle handle);
int MXNDArrayWaitAll(void);
/* dst = src (i == -1) or dst = src[i]; dtype-converting device copy. */
int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src, int i);
/* In-memory .params parse (same dmlc format as MXNDArrayLoad). */
int MXNDArrayLoadFromBuffer(const void *ndarray_buffer, size_t size,
                            mx_uint *out_size, NDArrayHandle **out_arr,
                            mx_uint *out_name_size, const char ***out_names);
/* Validates sparse-format invariants (sorted row ids, monotone indptr);
 * full_check also range-checks csr column indices. */
int MXNDArraySyncCheckFormat(NDArrayHandle handle, const int full_check);
/* Create an empty row_sparse/csr array.  num_aux/aux type/shape arrays
 * describe the index buffers (reference layout). */
int MXNDArrayCreateSparseEx(int storage_type, const mx_uint *shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, mx_uint num_aux,
                            int *aux_type, mx_uint *aux_ndims,
                            const mx_uint *aux_shape, NDArrayHandle *out);
/* Shared-memory NDArrays are a CPU-engine IPC mechanism with no TPU
 * analogue (device buffers are not shareable via shm; the DataLoader
 * uses its own IPC) — both fail with a descriptive error. */
int MXNDArrayGetSharedMemHandle(NDArrayHandle handle, int *shared_pid,
                                int *shared_id);
int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                 const mx_uint *shape, mx_uint ndim,
                                 int dtype, NDArrayHandle *out);

/* ---- autograd / custom extras --------------------------------------- */
/* Deprecated reference alias for backward() over the given outputs. */
int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle *output_handles);
/* The imperative tape does not rebuild Symbol graphs (records jax VJPs
 * instead) — fails with a descriptive error like the reference does for
 * unsupported graphs. */
int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle *out);
/* C-side custom-op registration: the supported extension points are
 * Python (mx.operator.register / autograd.Function) and Pallas
 * (rtc.PallasModule); both fail with a descriptive error. */
int MXCustomOpRegister(const char *op_type, void *creator);
int MXCustomFunctionRecord(int num_inputs, NDArrayHandle *inputs,
                           int num_outputs, NDArrayHandle *outputs,
                           void *callbacks);

/* ---- data-iter extras ----------------------------------------------- */
int MXDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                       uint64_t *out_size);
int MXDataIterGetIterInfo(const char *name, const char **out_name,
                          const char **description, mx_uint *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions);

/* ---- profile object ABI (reference c_api_profile.cc) ---------------- */
typedef void *ProfileHandle;

int MXProfileCreateDomain(const char *domain, ProfileHandle *out);
int MXProfileCreateTask(ProfileHandle domain, const char *task_name,
                        ProfileHandle *out);
int MXProfileCreateFrame(ProfileHandle domain, const char *frame_name,
                         ProfileHandle *out);
int MXProfileCreateEvent(const char *event_name, ProfileHandle *out);
int MXProfileCreateCounter(ProfileHandle domain, const char *counter_name,
                           ProfileHandle *out);
int MXProfileDestroyHandle(ProfileHandle frame_handle);
int MXProfileDurationStart(ProfileHandle duration_handle);
int MXProfileDurationStop(ProfileHandle duration_handle);
int MXProfileSetCounter(ProfileHandle counter_handle, uint64_t value);
int MXProfileAdjustCounter(ProfileHandle counter_handle, int64_t value);
int MXProfileSetMarker(ProfileHandle domain, const char *instant_marker_name,
                       const char *scope);

/* ---- quantization ABI (reference c_api_symbolic.cc) ----------------- */
/* Graph-only int8 pass: offline params become <name>_quantize
 * Variables, other weights quantize in-graph; attach calibration with
 * MXSetCalibTableToQuantizedSymbol. */
int MXQuantizeSymbol(SymbolHandle sym_handle, SymbolHandle *ret_sym_handle,
                     mx_uint num_excluded_symbols,
                     const char **excluded_symbols,
                     mx_uint num_offline, const char **offline_params,
                     const char *quantized_dtype);
int MXSetCalibTableToQuantizedSymbol(SymbolHandle qsym_handle,
                                     mx_uint num_layers,
                                     const char **layer_names,
                                     const float *min_ranges,
                                     const float *max_ranges,
                                     SymbolHandle *ret_sym_handle);
/* Subgraph-backend pass: XLA does whole-graph fusion internally, so the
 * pass is the identity (a fresh handle to the same graph). */
int MXGenBackendSubgraph(SymbolHandle sym_handle, const char *backend,
                         SymbolHandle *ret_sym_handle);

/* ---- legacy Function registry (deprecated in the reference) --------- */
typedef void *FunctionHandle;

int MXListFunctions(mx_uint *out_size, FunctionHandle **out_array);
int MXGetFunction(const char *name, FunctionHandle *out);
int MXFuncGetInfo(FunctionHandle fun, const char **name,
                  const char **description, mx_uint *num_args,
                  const char ***arg_names, const char ***arg_type_infos,
                  const char ***arg_descriptions,
                  const char **return_type);
int MXFuncDescribe(FunctionHandle fun, mx_uint *num_use_vars,
                   mx_uint *num_scalars, mx_uint *num_mutate_vars,
                   int *type_mask);
/* Positional invoke: use_vars are inputs, mutate_vars receive outputs.
 * Scalar args are not representable without names — pass them through
 * MXImperativeInvoke instead; num_scalars must be 0 here. */
int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars,
                 float *scalar_args, NDArrayHandle *mutate_vars);
int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                   float *scalar_args, NDArrayHandle *mutate_vars,
                   int num_params, char **param_keys, char **param_vals);

/* ---- runtime misc completion ---------------------------------------- */
typedef struct {
  const char *name;
  const unsigned char enabled;
} LibFeature;

/* Build/runtime feature flags (reference MXLibInfoFeatures). */
int MXLibInfoFeatures(const LibFeature **lib_features, size_t *size);
/* XLA manages host threading; accepted and ignored. */
int MXSetNumOMPThreads(int thread_num);
/* The XLA dispatch queue has no bulk-size knob; reports previous 0. */
int MXEngineSetBulkSize(int bulk_size, int *prev_bulk_size);
/* No CUDA devices in the TPU runtime: free = total = 0. */
int MXGetGPUMemoryInformation(int dev, int *free_mem, int *total_mem);
int MXGetGPUMemoryInformation64(int dev, uint64_t *free_mem,
                                uint64_t *total_mem);
/* CUDA RTC has no TPU analogue (user kernels are Pallas:
 * mxnet_tpu.rtc.PallasModule); all fail with a descriptive error. */
int MXRtcCreate(char *name, mx_uint num_input, mx_uint num_output,
                char **input_names, char **output_names,
                NDArrayHandle *inputs, NDArrayHandle *outputs, char *kernel,
                void **out);
int MXRtcPush(void *handle, mx_uint num_input, mx_uint num_output,
              NDArrayHandle *inputs, NDArrayHandle *outputs,
              mx_uint gridDimX, mx_uint gridDimY, mx_uint gridDimZ,
              mx_uint blockDimX, mx_uint blockDimY, mx_uint blockDimZ);
int MXRtcFree(void *handle);
int MXRtcCudaModuleCreate(const char *source, int num_options,
                          const char **options, int num_exports,
                          const char **exports, void **out);
int MXRtcCudaModuleFree(void *handle);
int MXRtcCudaKernelCreate(void *handle, const char *name, int num_args,
                          int *is_ndarray, int *is_const, int *arg_types,
                          void **out);
int MXRtcCudaKernelFree(void *handle);
int MXRtcCudaKernelCall(void *handle, int dev_id, void **args,
                        mx_uint grid_dim_x, mx_uint grid_dim_y,
                        mx_uint grid_dim_z, mx_uint block_dim_x,
                        mx_uint block_dim_y, mx_uint block_dim_z,
                        mx_uint shared_mem);

#ifdef __cplusplus
}
#endif
#endif /* MXTPU_C_API_H_ */
