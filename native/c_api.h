/*
 * Imperative C API: NDArray handles + operator invoke by name.
 *
 * Reference: include/mxnet/c_api.h (the 203-function ABI every frontend
 * marshals through — SURVEY §2.1 "C API" row) — here scoped to the
 * imperative data plane the C++ frontend needs: NDArray lifecycle,
 * host<->device copies, shape/dtype introspection, save/load, and
 * MXImperativeInvoke against the TPU op registry.  The implementation
 * (c_api.cc) embeds CPython and drives mxnet_tpu; every op executes as
 * a cached-jitted XLA computation on the TPU.
 *
 * Conventions match the reference ABI: every call returns 0 on success
 * and -1 on error with the message available from MXGetLastError()
 * (thread-local).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef void *NDArrayHandle;

/* dtype codes (reference python/mxnet/base.py _DTYPE_NP_TO_MX):
 * 0=float32 1=float64 2=float16 3=uint8 4=int32 5=int8 6=int64;
 * TPU-native extension: 7=bfloat16. */

const char *MXGetLastError(void);

/* Create a zero-initialized NDArray.  dev_type: 1 = cpu, 2 = tpu. */
int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int dtype, NDArrayHandle *out);

/* Synchronous host->device copy; size is in elements and must equal the
 * array's size.  `data` is interpreted in the array's dtype. */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size);

/* Synchronous device->host copy; size in elements and must equal the
 * array's element count (mirrors the FromCPU contract). */
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size);

/* Shape of the array; pointers valid until the next call on this handle. */
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_ndim,
                      const mx_uint **out_pdata);

int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);

int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);

/* Block until the array's async computation is complete (reference
 * WaitToRead — the sync point where deferred errors surface). */
int MXNDArrayWaitToRead(NDArrayHandle handle);

/* Block until all outstanding computation is complete. */
int MXNDArrayWaitAll(void);

int MXNDArrayFree(NDArrayHandle handle);

/* Save named arrays to the reference-compatible .params container. */
int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys);

/* Load a .params container.  Output pointers are owned by the library
 * and valid until the next MXNDArrayLoad on this thread; the handles
 * must each be freed with MXNDArrayFree. */
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);

/* Names of all registered operators.  Pointers owned by the library,
 * valid until the next call on this thread. */
int MXListAllOpNames(mx_uint *out_size, const char ***out_array);

/* Invoke a registered operator by name on NDArray inputs.  Scalar/tuple
 * hyper-parameters are passed as strings (reference convention: the
 * frontend stringifies, the backend parses against the op signature).
 * `*outputs` is set to a thread-local array of fresh handles (caller
 * frees each with MXNDArrayFree; the array itself is reused by the next
 * invoke on this thread). */
int MXImperativeInvoke(const char *op_name, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals);

/* ---------------------------------------------------------------------
 * Symbol ABI (reference src/c_api/c_api_symbolic.cc).  Graph
 * composition: atomic symbol + compose, JSON round trip, list
 * arguments/outputs/aux, shape inference.
 * ------------------------------------------------------------------ */
typedef void *SymbolHandle;

int MXSymbolCreateVariable(const char *name, SymbolHandle *out);

/* An atomic symbol holds an op + stringified hyper-params and must be
 * composed with inputs before use (MXSymbolCompose, which follows the
 * reference in updating the handle in place). */
int MXSymbolCreateAtomicSymbol(const char *op_name, mx_uint num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out);

/* Compose with inputs; keys may be NULL for positional args. */
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
/* JSON string owned by the library, valid until the next call on this
 * thread. */
int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json);

/* Name lists owned by the library, valid until the next symbol-list
 * call on this thread. */
int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                          const char ***out_array);
int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                        const char ***out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint *out_size,
                                const char ***out_array);

/* CSR-style shape exchange like the reference: arg_ind_ptr[i] indexes
 * into arg_shape_data for the i-th known arg; outputs come back in the
 * same layout (pointers valid until the next call on this thread).
 * complete is 1 when every returned shape is fully known. */
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char **keys, const mx_uint *arg_ind_ptr,
                       const mx_uint *arg_shape_data,
                       mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data, int *complete);

int MXSymbolFree(SymbolHandle sym);

/* ---------------------------------------------------------------------
 * Executor ABI (reference src/c_api/c_api_executor.cc).  grad_req codes
 * (OpReqType): 0 = null, 1 = write, 2 = add.
 * ------------------------------------------------------------------ */
typedef void *ExecutorHandle;

int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   mx_uint num_args, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store,
                   const mx_uint *grad_req_type, mx_uint aux_states_len,
                   NDArrayHandle *aux_states, ExecutorHandle *out);

int MXExecutorForward(ExecutorHandle ex, int is_train);

/* head gradients may be NULL/len 0 for loss heads */
int MXExecutorBackward(ExecutorHandle ex, mx_uint len,
                       NDArrayHandle *head_grads);

/* Fresh handles per call (caller frees each with MXNDArrayFree; the
 * array itself is reused by the next call on this thread). */
int MXExecutorOutputs(ExecutorHandle ex, mx_uint *out_size,
                      NDArrayHandle **out);

int MXExecutorFree(ExecutorHandle ex);

/* ---------------------------------------------------------------------
 * KVStore ABI (reference src/c_api/c_api.cc MXKVStore*).
 * ------------------------------------------------------------------ */
typedef void *KVStoreHandle;

int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreInit(KVStoreHandle kv, mx_uint num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle kv, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle kv, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStoreGetRank(KVStoreHandle kv, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle kv, int *size);
int MXKVStoreFree(KVStoreHandle kv);

/* ---------------------------------------------------------------------
 * Autograd ABI (reference src/c_api/c_api_ndarray.cc MXAutograd*):
 * imperative training without the executor — record, backward, read
 * grads.
 * ------------------------------------------------------------------ */
/* Returns the previous flag in *prev. */
int MXAutogradSetIsRecording(int is_recording, int *prev);
int MXAutogradSetIsTraining(int is_training, int *prev);

/* Attach gradient buffers to variables (grad_reqs: write). */
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle *var_handles,
                            NDArrayHandle *grad_handles);

/* Backward from outputs; head gradients may be NULL (loss heads). */
int MXAutogradBackward(mx_uint num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph);

/* Fresh handle for the gradient attached to this array (caller frees
 * with MXNDArrayFree). */
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out);

/* ---------------------------------------------------------------------
 * DataIter ABI (reference MXDataIter*): file-backed iterators created
 * by name with string params; Next() advances the cursor, Get*() read
 * the current batch.
 * ------------------------------------------------------------------ */
typedef void *DataIterHandle;

/* Names of creatable iterators (library-owned, valid until the next
 * call on this thread). */
int MXListDataIters(mx_uint *out_size, const char ***out_array);

int MXDataIterCreateIter(const char *name, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
/* *out = 1 while a batch is available. */
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
/* Fresh NDArray handles for the current batch (caller frees each). */
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);
int MXDataIterFree(DataIterHandle handle);

/* Reference-parity shutdown hook (engine teardown there; no-op here —
 * XLA teardown happens at process exit). */
int MXNotifyShutdown(void);

#ifdef __cplusplus
}
#endif
#endif /* MXTPU_C_API_H_ */
