// Imperative C ABI over an embedded CPython running mxnet_tpu
// (reference: src/c_api/c_api.cc + c_api_ndarray.cc:118-235 — there the
// ABI fronts the C++ engine/Imperative; here every invoke reaches the
// TPU op registry, whose ops are cached-jitted XLA computations, through
// mxnet_tpu.c_api_bridge).  Thread-safe via the GIL; errors land in the
// thread-local MXGetLastError string, matching the reference's
// MXAPIThreadLocalEntry error convention (src/c_api/c_api_error.cc).
#include "c_api.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

struct NDArrayObj {
  PyObject* array = nullptr;        // mxnet_tpu.ndarray.NDArray
  std::vector<mx_uint> shape_buf;   // backing for MXNDArrayGetShape
  std::vector<char> host_data;      // backing for MXNDArrayGetData
};

// thread-local result buffers (reference MXAPIThreadLocalEntry pattern:
// returned pointers stay valid until the next call on the same thread)
struct TLS {
  std::vector<NDArrayHandle> invoke_out;
  // load and op-name results use separate backing stores so calling
  // MXListAllOpNames does not invalidate a prior MXNDArrayLoad's names
  // (each is documented valid until the next call of the SAME kind)
  std::vector<std::string> load_str_store;
  std::vector<const char*> load_cstr_out;
  std::vector<NDArrayHandle> load_out;
  std::vector<std::string> op_str_store;
  std::vector<const char*> op_cstr_out;
};
TLS* tls() {
  thread_local TLS t;
  return &t;
}

int fail(const std::string& msg) {
  g_error = msg;
  return -1;
}

// PyUnicode_AsUTF8 returns nullptr on non-UTF8 names; constructing a
// std::string from nullptr is UB.  safe_utf8 is for diagnostic text only
// (error messages); data paths returning names to the caller must use
// utf8_or_null and propagate an error instead of renaming silently.
const char* safe_utf8(PyObject* s) {
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (!c) {
    PyErr_Clear();
    return "<non-utf8>";
  }
  return c;
}

const char* utf8_or_null(PyObject* s) {
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (!c) PyErr_Clear();
  return c;
}

int fail_py(const char* what) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = what;
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      msg += ": ";
      msg += safe_utf8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return fail(msg);
}

void ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    PyEval_SaveThread();
  }
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// import mxnet_tpu.c_api_bridge and fetch `name` (new reference)
PyObject* bridge_fn(const char* name) {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.c_api_bridge");
  if (!mod) return nullptr;
  PyObject* fn = PyObject_GetAttrString(mod, name);
  Py_DECREF(mod);
  return fn;
}

NDArrayObj* wrap(PyObject* array) {
  auto* obj = new NDArrayObj();
  obj->array = array;  // steals the reference
  return obj;
}

}  // namespace

extern "C" {

const char* MXGetLastError(void) { return g_error.c_str(); }

int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int dtype, NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("create");
  if (!fn) return fail_py("c_api_bridge.create not found");
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* arr =
      PyObject_CallFunction(fn, "Oiii", shp, dev_type, dev_id, dtype);
  Py_DECREF(shp);
  Py_DECREF(fn);
  if (!arr) return fail_py("NDArray create failed");
  *out = wrap(arr);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* eb = bridge_fn("element_bytes");
  if (!eb) return fail_py("bridge missing");
  PyObject* nbytes = PyObject_CallFunction(eb, "O", obj->array);
  Py_DECREF(eb);
  if (!nbytes) return fail_py("element size failed");
  size_t itemsize = PyLong_AsSize_t(nbytes);
  Py_DECREF(nbytes);
  PyObject* fn = bridge_fn("copy_from_bytes");
  if (!fn) return fail_py("bridge missing");
  PyObject* buf = PyBytes_FromStringAndSize(static_cast<const char*>(data),
                                            size * itemsize);
  PyObject* r = PyObject_CallFunction(fn, "OO", obj->array, buf);
  Py_DECREF(buf);
  Py_DECREF(fn);
  if (!r) return fail_py("copy from cpu failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* fn = bridge_fn("to_bytes");
  if (!fn) return fail_py("bridge missing");
  PyObject* bytes = PyObject_CallFunction(fn, "O", obj->array);
  Py_DECREF(fn);
  if (!bytes) return fail_py("copy to cpu failed");
  size_t blen = static_cast<size_t>(PyBytes_Size(bytes));
  size_t nelem = 0;
  {
    PyObject* sz = PyObject_GetAttrString(obj->array, "size");
    nelem = sz ? PyLong_AsSize_t(sz) : 0;
    Py_XDECREF(sz);
  }
  if (size != nelem) {
    // mirror the FromCPU contract exactly: the caller must pass the
    // element count, not merely a large-enough buffer
    Py_DECREF(bytes);
    return fail("destination size must equal array element count");
  }
  std::memcpy(data, PyBytes_AsString(bytes), blen);
  Py_DECREF(bytes);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_ndim,
                      const mx_uint** out_pdata) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* shape = PyObject_GetAttrString(obj->array, "shape");
  if (!shape) return fail_py("shape failed");
  Py_ssize_t n = PyTuple_Size(shape);
  obj->shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    obj->shape_buf[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, i)));
  Py_DECREF(shape);
  *out_ndim = static_cast<mx_uint>(n);
  *out_pdata = obj->shape_buf.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* fn = bridge_fn("dtype_code");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, "O", obj->array);
  Py_DECREF(fn);
  if (!r) return fail_py("dtype failed");
  *out_dtype = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* fn = bridge_fn("context_of");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, "O", obj->array);
  Py_DECREF(fn);
  if (!r) return fail_py("context failed");
  *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 0)));
  *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 1)));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitToRead(NDArrayHandle handle) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = PyObject_CallMethod(obj->array, "wait_to_read", nullptr);
  if (!r) return fail_py("wait_to_read failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitAll(void) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("wait_all");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, nullptr);
  Py_DECREF(fn);
  if (!r) return fail_py("wait_all failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  Py_XDECREF(obj->array);
  delete obj;
  return 0;
}

int MXNDArraySave(const char* fname, mx_uint num_args, NDArrayHandle* args,
                  const char** keys) {
  Gil gil;
  PyObject* arrs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* a = static_cast<NDArrayObj*>(args[i])->array;
    Py_INCREF(a);
    PyList_SET_ITEM(arrs, i, a);
  }
  PyObject* names;
  if (keys) {
    names = PyList_New(num_args);
    for (mx_uint i = 0; i < num_args; ++i)
      PyList_SET_ITEM(names, i, PyUnicode_FromString(keys[i]));
  } else {
    names = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* fn = bridge_fn("save");
  if (!fn) {
    Py_DECREF(arrs);
    Py_DECREF(names);
    return fail_py("bridge missing");
  }
  PyObject* r = PyObject_CallFunction(fn, "sOO", fname, arrs, names);
  Py_DECREF(fn);
  Py_DECREF(arrs);
  Py_DECREF(names);
  if (!r) return fail_py("save failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("load");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, "s", fname);
  Py_DECREF(fn);
  if (!r) return fail_py("load failed");
  PyObject* names = PyTuple_GET_ITEM(r, 0);
  PyObject* arrays = PyTuple_GET_ITEM(r, 1);
  TLS* t = tls();
  t->load_out.clear();
  t->load_str_store.clear();
  t->load_cstr_out.clear();
  Py_ssize_t n = PyList_Size(arrays);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(arrays, i);
    Py_INCREF(a);
    t->load_out.push_back(wrap(a));
  }
  Py_ssize_t nn = PyList_Size(names);
  for (Py_ssize_t i = 0; i < nn; ++i) {
    const char* name = utf8_or_null(PyList_GET_ITEM(names, i));
    if (!name) {
      Py_DECREF(r);
      return fail("non-UTF8 array name in file");
    }
    t->load_str_store.push_back(name);
  }
  for (auto& s : t->load_str_store) t->load_cstr_out.push_back(s.c_str());
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(t->load_out.size());
  *out_arr = t->load_out.data();
  *out_name_size = static_cast<mx_uint>(t->load_cstr_out.size());
  *out_names = t->load_cstr_out.data();
  return 0;
}

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("list_ops");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, nullptr);
  Py_DECREF(fn);
  if (!r) return fail_py("list_ops failed");
  TLS* t = tls();
  t->op_str_store.clear();
  t->op_cstr_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* name = utf8_or_null(PyList_GET_ITEM(r, i));
    if (!name) {
      Py_DECREF(r);
      return fail("non-UTF8 op name");
    }
    t->op_str_store.push_back(name);
  }
  Py_DECREF(r);
  for (auto& s : t->op_str_store) t->op_cstr_out.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(t->op_cstr_out.size());
  *out_array = t->op_cstr_out.data();
  return 0;
}

int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("invoke");
  if (!fn) return fail_py("bridge missing");
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* a = static_cast<NDArrayObj*>(inputs[i])->array;
    Py_INCREF(a);
    PyList_SET_ITEM(ins, i, a);
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* r =
      PyObject_CallFunction(fn, "sOOO", op_name, ins, keys, vals);
  Py_DECREF(fn);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!r) return fail_py("invoke failed");
  TLS* t = tls();
  t->invoke_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(r, i);
    Py_INCREF(a);
    t->invoke_out.push_back(wrap(a));
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(t->invoke_out.size());
  *outputs = t->invoke_out.data();
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------
// Symbol / Executor / KVStore ABI (reference c_api_symbolic.cc,
// c_api_executor.cc, MXKVStore*).  Handles are PyObject* boxes; the
// graph/executor logic lives in mxnet_tpu and is reached through the
// same bridge module as the NDArray plane.
// ---------------------------------------------------------------------

namespace {

struct PyHandle {
  PyObject* obj = nullptr;
};

PyHandle* wrap_py(PyObject* obj) {
  auto* h = new PyHandle();
  h->obj = obj;  // steals the reference
  return h;
}

// TLS stores for the symbol/executor plane
struct SymTLS {
  std::vector<std::string> str_store;
  std::vector<const char*> cstr_out;
  std::string json_store;
  // MXSymbolInferShape backing: three groups of (ndim, flat rows, row
  // pointers)
  std::vector<mx_uint> ndims[3];
  std::vector<std::vector<mx_uint>> rows[3];
  std::vector<const mx_uint*> row_ptrs[3];
  std::vector<NDArrayHandle> exec_out;
};
SymTLS* sym_tls() {
  thread_local SymTLS t;
  return &t;
}

// call bridge fn with pre-built args tuple; returns new ref or null
PyObject* call_bridge(const char* name, PyObject* args) {
  PyObject* fn = bridge_fn(name);
  if (!fn) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* r = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  return r;
}

PyObject* str_list(mx_uint n, const char** strs) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyUnicode_FromString(strs ? strs[i] : ""));
  return lst;
}

// list of borrowed NDArray objects (NULL handles become None)
PyObject* nd_list(mx_uint n, NDArrayHandle* arr) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyObject* a = arr && arr[i]
        ? static_cast<NDArrayObj*>(arr[i])->array : Py_None;
    Py_INCREF(a);
    PyList_SET_ITEM(lst, i, a);
  }
  return lst;
}

int return_str_list(PyObject* r, mx_uint* out_size,
                    const char*** out_array) {
  SymTLS* t = sym_tls();
  t->str_store.clear();
  t->cstr_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = utf8_or_null(PyList_GET_ITEM(r, i));
    if (!s) {
      Py_DECREF(r);
      return fail("non-UTF8 name");
    }
    t->str_store.push_back(s);
  }
  Py_DECREF(r);
  for (auto& s : t->str_store) t->cstr_out.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(t->cstr_out.size());
  *out_array = t->cstr_out.data();
  return 0;
}

}  // namespace

extern "C" {

int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("symbol_create_variable",
                            Py_BuildValue("(s)", name));
  if (!r) return fail_py("create variable failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolCreateAtomicSymbol(const char* op_name, mx_uint num_param,
                               const char** keys, const char** vals,
                               SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(op_name));
  PyTuple_SET_ITEM(args, 1, str_list(num_param, keys));
  PyTuple_SET_ITEM(args, 2, str_list(num_param, vals));
  PyObject* r = call_bridge("symbol_create_atomic", args);
  if (!r) return fail_py("create atomic symbol failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* sym_args) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* arg_list = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* a = static_cast<PyHandle*>(sym_args[i])->obj;
    Py_INCREF(a);
    PyList_SET_ITEM(arg_list, i, a);
  }
  PyObject* args = PyTuple_New(4);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, name ? PyUnicode_FromString(name)
                                 : (Py_INCREF(Py_None), Py_None));
  PyTuple_SET_ITEM(args, 2, str_list(keys ? num_args : 0, keys));
  PyTuple_SET_ITEM(args, 3, arg_list);
  PyObject* r = call_bridge("symbol_compose", args);
  if (!r) return fail_py("compose failed");
  // reference semantics: compose updates the handle in place
  Py_DECREF(h->obj);
  h->obj = r;
  return 0;
}

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("symbol_from_json",
                            Py_BuildValue("(s)", json));
  if (!r) return fail_py("symbol from json failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_to_json",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("symbol to json failed");
  const char* s = utf8_or_null(r);
  if (!s) {
    Py_DECREF(r);
    return fail("non-UTF8 json");
  }
  sym_tls()->json_store = s;
  Py_DECREF(r);
  *out_json = sym_tls()->json_store.c_str();
  return 0;
}

#define MXTPU_SYM_LIST(fn_name, bridge_name)                            \
  int fn_name(SymbolHandle sym, mx_uint* out_size,                      \
              const char*** out_array) {                                \
    ensure_python();                                                    \
    Gil gil;                                                            \
    auto* h = static_cast<PyHandle*>(sym);                              \
    PyObject* r = call_bridge(bridge_name,                              \
                              Py_BuildValue("(O)", h->obj));            \
    if (!r) return fail_py(bridge_name " failed");                      \
    return return_str_list(r, out_size, out_array);                     \
  }

MXTPU_SYM_LIST(MXSymbolListArguments, "symbol_list_arguments")
MXTPU_SYM_LIST(MXSymbolListOutputs, "symbol_list_outputs")
MXTPU_SYM_LIST(MXSymbolListAuxiliaryStates, "symbol_list_aux")
#undef MXTPU_SYM_LIST

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* key_list = str_list(num_args, keys);
  PyObject* ndims = PyList_New(num_args);
  mx_uint total = num_args ? arg_ind_ptr[num_args] : 0;
  PyObject* flat = PyList_New(total);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SET_ITEM(ndims, i, PyLong_FromUnsignedLong(
        arg_ind_ptr[i + 1] - arg_ind_ptr[i]));
  for (mx_uint i = 0; i < total; ++i)
    PyList_SET_ITEM(flat, i, PyLong_FromUnsignedLong(arg_shape_data[i]));
  PyObject* args = PyTuple_New(4);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, key_list);
  PyTuple_SET_ITEM(args, 2, ndims);
  PyTuple_SET_ITEM(args, 3, flat);
  PyObject* r = call_bridge("symbol_infer_shape", args);
  if (!r) return fail_py("infer shape failed");
  // r = (arg_ndims, arg_flat, out_ndims, out_flat, aux_ndims, aux_flat)
  SymTLS* t = sym_tls();
  int all_known = 1;
  mx_uint* sizes[3] = {in_shape_size, out_shape_size, aux_shape_size};
  const mx_uint** ndim_outs[3] = {in_shape_ndim, out_shape_ndim,
                                  aux_shape_ndim};
  const mx_uint*** data_outs[3] = {in_shape_data, out_shape_data,
                                   aux_shape_data};
  for (int g = 0; g < 3; ++g) {
    PyObject* nd_l = PyTuple_GetItem(r, 2 * g);
    PyObject* fl_l = PyTuple_GetItem(r, 2 * g + 1);
    t->ndims[g].clear();
    t->rows[g].clear();
    t->row_ptrs[g].clear();
    Py_ssize_t n = PyList_Size(nd_l);
    Py_ssize_t pos = 0;
    for (Py_ssize_t i = 0; i < n; ++i) {
      mx_uint nd_i = static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyList_GET_ITEM(nd_l, i)));
      t->ndims[g].push_back(nd_i);
      std::vector<mx_uint> row;
      for (mx_uint j = 0; j < nd_i; ++j, ++pos)
        row.push_back(static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyList_GET_ITEM(fl_l, pos))));
      if (nd_i == 0) all_known = 0;
      t->rows[g].push_back(std::move(row));
    }
    for (auto& row : t->rows[g]) t->row_ptrs[g].push_back(row.data());
    *sizes[g] = static_cast<mx_uint>(t->ndims[g].size());
    *ndim_outs[g] = t->ndims[g].data();
    *data_outs[g] = t->row_ptrs[g].data();
  }
  Py_DECREF(r);
  if (complete) *complete = all_known;
  return 0;
}

int MXSymbolFree(SymbolHandle sym) {
  if (!sym) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   mx_uint num_args, NDArrayHandle* in_args,
                   NDArrayHandle* arg_grad_store,
                   const mx_uint* grad_req_type, mx_uint aux_states_len,
                   NDArrayHandle* aux_states, ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* reqs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SET_ITEM(reqs, i,
                    PyLong_FromUnsignedLong(grad_req_type[i]));
  PyObject* args = PyTuple_New(7);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(dev_type));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(dev_id));
  PyTuple_SET_ITEM(args, 3, nd_list(num_args, in_args));
  PyTuple_SET_ITEM(args, 4, nd_list(num_args, arg_grad_store));
  PyTuple_SET_ITEM(args, 5, reqs);
  PyTuple_SET_ITEM(args, 6, nd_list(aux_states_len, aux_states));
  PyObject* r = call_bridge("executor_bind", args);
  if (!r) return fail_py("executor bind failed");
  *out = wrap_py(r);
  return 0;
}

int MXExecutorForward(ExecutorHandle ex, int is_train) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* r = call_bridge("executor_forward",
                            Py_BuildValue("(Oi)", h->obj, is_train));
  if (!r) return fail_py("executor forward failed");
  Py_DECREF(r);  // outputs re-fetched via MXExecutorOutputs
  return 0;
}

int MXExecutorBackward(ExecutorHandle ex, mx_uint len,
                       NDArrayHandle* head_grads) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* args = PyTuple_New(2);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, nd_list(len, head_grads));
  PyObject* r = call_bridge("executor_backward", args);
  if (!r) return fail_py("executor backward failed");
  Py_DECREF(r);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle ex, mx_uint* out_size,
                      NDArrayHandle** out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* r = call_bridge("executor_outputs",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("executor outputs failed");
  SymTLS* t = sym_tls();
  t->exec_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(r, i);
    Py_INCREF(a);
    t->exec_out.push_back(wrap(a));
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(t->exec_out.size());
  *out = t->exec_out.data();
  return 0;
}

int MXExecutorFree(ExecutorHandle ex) {
  if (!ex) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("kv_create", Py_BuildValue("(s)", type));
  if (!r) return fail_py("kvstore create failed");
  *out = wrap_py(r);
  return 0;
}

namespace {
int kv_keyed_call(const char* bridge_name, KVStoreHandle kv, mx_uint num,
                  const int* keys, NDArrayHandle* vals, int priority,
                  bool with_priority) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* key_list = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SET_ITEM(key_list, i, PyLong_FromLong(keys[i]));
  PyObject* args = PyTuple_New(with_priority ? 4 : 3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, key_list);
  PyTuple_SET_ITEM(args, 2, nd_list(num, vals));
  if (with_priority)
    PyTuple_SET_ITEM(args, 3, PyLong_FromLong(priority));
  PyObject* r = call_bridge(bridge_name, args);
  if (!r) return fail_py("kvstore call failed");
  Py_DECREF(r);
  return 0;
}
}  // namespace

int MXKVStoreInit(KVStoreHandle kv, mx_uint num, const int* keys,
                  NDArrayHandle* vals) {
  return kv_keyed_call("kv_init", kv, num, keys, vals, 0, false);
}

int MXKVStorePush(KVStoreHandle kv, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  return kv_keyed_call("kv_push", kv, num, keys, vals, priority, true);
}

int MXKVStorePull(KVStoreHandle kv, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  return kv_keyed_call("kv_pull", kv, num, keys, vals, priority, true);
}

int MXKVStoreGetRank(KVStoreHandle kv, int* rank) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_rank", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("kv rank failed");
  *rank = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetGroupSize(KVStoreHandle kv, int* size) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_num_workers",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("kv num_workers failed");
  *size = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreFree(KVStoreHandle kv) {
  if (!kv) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXAutogradSetIsRecording(int is_recording, int* prev) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("autograd_set_recording",
                            Py_BuildValue("(i)", is_recording));
  if (!r) return fail_py("set recording failed");
  if (prev) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradSetIsTraining(int is_training, int* prev) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("autograd_set_training",
                            Py_BuildValue("(i)", is_training));
  if (!r) return fail_py("set training failed");
  if (prev) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            NDArrayHandle* grad_handles) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, nd_list(num_var, var_handles));
  PyTuple_SET_ITEM(args, 1, nd_list(num_var, grad_handles));
  PyObject* r = call_bridge("autograd_mark_variables", args);
  if (!r) return fail_py("mark variables failed");
  Py_DECREF(r);
  return 0;
}

int MXAutogradBackward(mx_uint num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(4);
  PyTuple_SET_ITEM(args, 0, nd_list(num_output, output_handles));
  PyTuple_SET_ITEM(args, 1,
                   nd_list(ograd_handles ? num_output : 0,
                           ograd_handles));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(retain_graph));
  PyTuple_SET_ITEM(args, 3, PyLong_FromLong(1));
  PyObject* r = call_bridge("autograd_backward", args);
  if (!r) return fail_py("backward failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("ndarray_get_grad",
                            Py_BuildValue("(O)", obj->array));
  if (!r) return fail_py("get grad failed");
  *out = wrap(r);
  return 0;
}

int MXListDataIters(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("dataiter_list", PyTuple_New(0));
  if (!r) return fail_py("list data iters failed");
  return return_str_list(r, out_size, out_array);
}

int MXDataIterCreateIter(const char* name, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(name));
  PyTuple_SET_ITEM(args, 1, str_list(num_param, keys));
  PyTuple_SET_ITEM(args, 2, str_list(num_param, vals));
  PyObject* r = call_bridge("dataiter_create", args);
  if (!r) return fail_py("create data iter failed");
  *out = wrap_py(r);
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("dataiter_next",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter next failed");
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("dataiter_before_first",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter reset failed");
  Py_DECREF(r);
  return 0;
}

namespace {
int iter_get_array(const char* fn, DataIterHandle handle,
                   NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge(fn, Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter get failed");
  *out = wrap(r);
  return 0;
}
}  // namespace

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out) {
  return iter_get_array("dataiter_get_data", handle, out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out) {
  return iter_get_array("dataiter_get_label", handle, out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int* pad) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("dataiter_get_pad",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter pad failed");
  *pad = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXDataIterFree(DataIterHandle handle) {
  if (!handle) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXNotifyShutdown(void) { return 0; }

}  // extern "C"

// ---------------------------------------------------------------------
// Round-4 planes: NDArray extras, DLPack, CachedOp, KVStore extras,
// RecordIO, profiler, symbol extras, executor monitor, autograd extras,
// runtime misc (reference: c_api.cc / c_api_ndarray.cc:235 /
// c_api_symbolic.cc / c_api_profile.cc).
// ---------------------------------------------------------------------

namespace {

// extra thread-local result stores for the round-4 planes
struct ExtTLS {
  std::vector<int> stypes;
  std::vector<NDArrayHandle> cached_out;
  std::vector<NDArrayHandle> grad_out;
  std::vector<int> grad_stypes;
  std::string raw_bytes;
  std::string record_buf;
  std::string agg_stats;
  std::string attr_value;
  std::string kv_type;
  // op-introspection backing (MXSymbolGetAtomicSymbolInfo)
  std::vector<std::string> op_doc_store;
  std::vector<const char*> op_doc_ptrs[3];
  std::string op_name, op_desc;
  std::vector<void*> creators;
  std::vector<std::string> creator_names;  // filled with creators
};
ExtTLS* ext_tls() {
  thread_local ExtTLS t;
  return &t;
}

// take a bridge-returned ([outputs...], [stypes...]) pair into TLS
int unpack_outs_stypes(PyObject* r, std::vector<NDArrayHandle>* out_store,
                       int* num_outputs, NDArrayHandle** outputs,
                       const int** out_stypes) {
  PyObject* outs = PyTuple_GET_ITEM(r, 0);
  PyObject* sts = PyTuple_GET_ITEM(r, 1);
  out_store->clear();
  ExtTLS* e = ext_tls();
  e->stypes.clear();
  Py_ssize_t n = PyList_Size(outs);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(outs, i);
    Py_INCREF(a);
    out_store->push_back(wrap(a));
    e->stypes.push_back(
        static_cast<int>(PyLong_AsLong(PyList_GET_ITEM(sts, i))));
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(out_store->size());
  *outputs = out_store->data();
  if (out_stypes) *out_stypes = e->stypes.data();
  return 0;
}

// minimal DLPack 0.x ABI structs (standard layout)
struct DLDevice_ {
  int32_t device_type;
  int32_t device_id;
};
struct DLDataType_ {
  uint8_t code;
  uint8_t bits;
  uint16_t lanes;
};
struct DLTensor_ {
  void* data;
  DLDevice_ device;
  int32_t ndim;
  DLDataType_ dtype;
  int64_t* shape;
  int64_t* strides;
  uint64_t byte_offset;
};
struct DLManagedTensor_ {
  DLTensor_ dl_tensor;
  void* manager_ctx;
  void (*deleter)(DLManagedTensor_*);
};

// C-callback trampolines: a PyCFunction whose self is a capsule holding
// the user's function pointer + closure handle
struct UpdaterCtx {
  MXKVStoreUpdater fn = nullptr;
  MXKVStoreStrUpdater str_fn = nullptr;
  void* handle = nullptr;
};

PyObject* updater_trampoline(PyObject* self, PyObject* args) {
  auto* ctx = static_cast<UpdaterCtx*>(
      PyCapsule_GetPointer(self, "mxtpu.updater"));
  PyObject *key, *recv, *local;
  if (!ctx || !PyArg_ParseTuple(args, "OOO", &key, &recv, &local))
    return nullptr;
  // handles are owned by this call; the user callback must not free them
  Py_INCREF(recv);
  Py_INCREF(local);
  NDArrayObj* r = wrap(recv);
  NDArrayObj* l = wrap(local);
  bool handled = false;
  if (PyLong_Check(key)) {
    int k = static_cast<int>(PyLong_AsLong(key));
    if (ctx->fn) {
      ctx->fn(k, r, l, ctx->handle);
      handled = true;
    } else if (ctx->str_fn) {
      // string-only updaters still see every key (stringified int)
      std::string ks = std::to_string(k);
      ctx->str_fn(ks.c_str(), r, l, ctx->handle);
      handled = true;
    }
  } else {
    const char* k = utf8_or_null(key);
    if (ctx->str_fn && k) {
      ctx->str_fn(k, r, l, ctx->handle);
      handled = true;
    }
    // an int-only updater CANNOT consume a string key faithfully —
    // hashing would alias per-key optimizer state; fail loudly below
  }
  MXNDArrayFree(r);
  MXNDArrayFree(l);
  if (!handled) {
    PyErr_SetString(
        PyExc_ValueError,
        "kvstore updater cannot handle this key kind: install a string "
        "updater (MXKVStoreSetUpdaterEx) for string/host-row keys");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyMethodDef updater_def = {"mxtpu_updater", updater_trampoline,
                           METH_VARARGS, nullptr};

struct MonitorCtx {
  ExecutorMonitorCallback fn = nullptr;
  void* handle = nullptr;
};

PyObject* monitor_trampoline(PyObject* self, PyObject* args) {
  auto* ctx = static_cast<MonitorCtx*>(
      PyCapsule_GetPointer(self, "mxtpu.monitor"));
  PyObject *name, *arr;
  if (!ctx || !PyArg_ParseTuple(args, "OO", &name, &arr)) return nullptr;
  const char* n = utf8_or_null(name);
  if (n && ctx->fn) {
    Py_INCREF(arr);
    NDArrayObj* a = wrap(arr);
    ctx->fn(n, a, ctx->handle);
    MXNDArrayFree(a);
  }
  Py_RETURN_NONE;
}

PyMethodDef monitor_def = {"mxtpu_monitor", monitor_trampoline,
                           METH_VARARGS, nullptr};

void capsule_free_updater(PyObject* cap) {
  delete static_cast<UpdaterCtx*>(
      PyCapsule_GetPointer(cap, "mxtpu.updater"));
}

void capsule_free_monitor(PyObject* cap) {
  delete static_cast<MonitorCtx*>(
      PyCapsule_GetPointer(cap, "mxtpu.monitor"));
}

// fresh NDArray handle from a bridge call returning one array
int return_one_array(PyObject* r, const char* what, NDArrayHandle* out) {
  if (!r) return fail_py(what);
  *out = wrap(r);
  return 0;
}

}  // namespace

extern "C" {

// -- NDArray extras ----------------------------------------------------

int MXNDArrayCreateNone(NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  return return_one_array(call_bridge("create_none", PyTuple_New(0)),
                          "create none failed", out);
}

int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out) {
  (void)delay_alloc;  // XLA owns allocation
  return MXNDArrayCreate(shape, ndim, dev_type, dev_id, dtype, out);
}

int MXNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                   NDArrayHandle* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge(
      "nd_slice", Py_BuildValue("(OII)", obj->array, begin, end));
  return return_one_array(r, "slice failed", out);
}

int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r =
      call_bridge("nd_at", Py_BuildValue("(OI)", obj->array, idx));
  return return_one_array(r, "at failed", out);
}

static int reshape_impl(NDArrayHandle handle, int ndim,
                        const long long* dims, NDArrayHandle* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* dl = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyList_SET_ITEM(dl, i, PyLong_FromLongLong(dims[i]));
  PyObject* r =
      call_bridge("nd_reshape", Py_BuildValue("(OO)", obj->array, dl));
  Py_DECREF(dl);
  return return_one_array(r, "reshape failed", out);
}

int MXNDArrayReshape(NDArrayHandle handle, int ndim, const int* dims,
                     NDArrayHandle* out) {
  std::vector<long long> d(dims, dims + ndim);
  return reshape_impl(handle, ndim, d.data(), out);
}

int MXNDArrayReshape64(NDArrayHandle handle, int ndim,
                       const long long* dims, NDArrayHandle* out) {
  return reshape_impl(handle, ndim, dims, out);
}

int MXNDArrayGetStorageType(NDArrayHandle handle, int* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("storage_type_code",
                            Py_BuildValue("(O)", obj->array));
  if (!r) return fail_py("storage type failed");
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r =
      call_bridge("nd_detach", Py_BuildValue("(O)", obj->array));
  return return_one_array(r, "detach failed", out);
}

int MXNDArraySetGradState(NDArrayHandle handle, int state) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("nd_set_grad_state",
                            Py_BuildValue("(Oi)", obj->array, state));
  if (!r) return fail_py("set grad state failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetGradState(NDArrayHandle handle, int* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("nd_get_grad_state",
                            Py_BuildValue("(O)", obj->array));
  if (!r) return fail_py("get grad state failed");
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t* out_size,
                          const char** out_buf) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("nd_save_raw_bytes",
                            Py_BuildValue("(O)", obj->array));
  if (!r) return fail_py("save raw bytes failed");
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    return fail_py("raw bytes not bytes");
  }
  ExtTLS* e = ext_tls();
  e->raw_bytes.assign(buf, static_cast<size_t>(len));
  Py_DECREF(r);
  *out_size = e->raw_bytes.size();
  *out_buf = e->raw_bytes.data();
  return 0;
}

int MXNDArrayLoadFromRawBytes(const void* buf, size_t size,
                              NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* bytes =
      PyBytes_FromStringAndSize(static_cast<const char*>(buf), size);
  PyObject* r = call_bridge("nd_load_from_raw_bytes",
                            Py_BuildValue("(N)", bytes));
  return return_one_array(r, "load raw bytes failed", out);
}

int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r =
      call_bridge("nd_data_ndarray", Py_BuildValue("(O)", obj->array));
  return return_one_array(r, "data ndarray failed", out);
}

int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("nd_aux_ndarray",
                            Py_BuildValue("(OI)", obj->array, i));
  return return_one_array(r, "aux ndarray failed", out);
}

int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int* out_type) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("nd_aux_type_code",
                            Py_BuildValue("(OI)", obj->array, i));
  if (!r) return fail_py("aux type failed");
  *out_type = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXImperativeInvokeEx(const char* op_name, int num_inputs,
                         NDArrayHandle* inputs, int* num_outputs,
                         NDArrayHandle** outputs, int num_params,
                         const char** param_keys, const char** param_vals,
                         const int** out_stypes) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(4);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(op_name));
  PyTuple_SET_ITEM(args, 1,
                   nd_list(static_cast<mx_uint>(num_inputs), inputs));
  PyTuple_SET_ITEM(args, 2, str_list(num_params, param_keys));
  PyTuple_SET_ITEM(args, 3, str_list(num_params, param_vals));
  PyObject* r = call_bridge("invoke_ex", args);
  if (!r) return fail_py("invoke failed");
  return unpack_outs_stypes(r, &tls()->invoke_out, num_outputs, outputs,
                            out_stypes);
}

// -- DLPack ------------------------------------------------------------

int MXNDArrayToDLPack(NDArrayHandle handle, DLManagedTensorHandle* out) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* np = call_bridge("to_numpy_retained",
                             Py_BuildValue("(O)", obj->array));
  if (!np) return fail_py("to numpy failed");
  PyObject* cap = PyObject_CallMethod(np, "__dlpack__", nullptr);
  Py_DECREF(np);  // the capsule's manager_ctx keeps the buffer alive
  if (!cap) return fail_py("__dlpack__ failed");
  void* dl = PyCapsule_GetPointer(cap, "dltensor");
  if (!dl) {
    Py_DECREF(cap);
    return fail_py("not a dltensor capsule");
  }
  // consume the capsule (standard protocol): ownership moves to caller
  PyCapsule_SetName(cap, "used_dltensor");
  PyCapsule_SetDestructor(cap, nullptr);
  Py_DECREF(cap);
  *out = dl;
  return 0;
}

int MXNDArrayFromDLPack(DLManagedTensorHandle dlpack, NDArrayHandle* out) {
  return MXNDArrayFromDLPackEx(dlpack, 0, out);
}

int MXNDArrayFromDLPackEx(DLManagedTensorHandle dlpack,
                          const int transient_handle, NDArrayHandle* out) {
  (void)transient_handle;
  ensure_python();
  Gil gil;
  PyObject* cap = PyCapsule_New(dlpack, "dltensor", nullptr);
  if (!cap) return fail_py("capsule failed");
  PyObject* r =
      call_bridge("from_dlpack_capsule", Py_BuildValue("(N)", cap));
  return return_one_array(r, "from dlpack failed", out);
}

int MXNDArrayCallDLPackDeleter(DLManagedTensorHandle dlpack) {
  if (!dlpack) return 0;
  auto* dl = static_cast<DLManagedTensor_*>(dlpack);
  if (dl->deleter) dl->deleter(dl);
  return 0;
}

// -- CachedOp ----------------------------------------------------------

int MXCreateCachedOp(SymbolHandle sym, CachedOpHandle* out) {
  return MXCreateCachedOpEx(sym, 0, nullptr, nullptr, out);
}

int MXCreateCachedOpEx(SymbolHandle sym, int num_flags, const char** keys,
                       const char** vals, CachedOpHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* args = PyTuple_New(3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(num_flags, keys));
  PyTuple_SET_ITEM(args, 2, str_list(num_flags, vals));
  PyObject* r = call_bridge("cached_op_create", args);
  if (!r) return fail_py("cached op create failed");
  *out = wrap_py(r);
  return 0;
}

int MXFreeCachedOp(CachedOpHandle handle) {
  if (!handle) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle* inputs, int* num_outputs,
                     NDArrayHandle** outputs) {
  return MXInvokeCachedOpEx(handle, num_inputs, inputs, num_outputs,
                            outputs, nullptr);
}

int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, const int** out_stypes) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* args = PyTuple_New(2);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1,
                   nd_list(static_cast<mx_uint>(num_inputs), inputs));
  PyObject* r = call_bridge("cached_op_invoke", args);
  if (!r) return fail_py("cached op invoke failed");
  return unpack_outs_stypes(r, &ext_tls()->cached_out, num_outputs,
                            outputs, out_stypes);
}

// -- KVStore extras ----------------------------------------------------

int MXKVStoreSetUpdater(KVStoreHandle kv, MXKVStoreUpdater updater,
                        void* updater_handle) {
  return MXKVStoreSetUpdaterEx(kv, updater, nullptr, updater_handle);
}

int MXKVStoreSetUpdaterEx(KVStoreHandle kv, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void* updater_handle) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  auto* ctx = new UpdaterCtx{updater, str_updater, updater_handle};
  PyObject* cap =
      PyCapsule_New(ctx, "mxtpu.updater", capsule_free_updater);
  PyObject* cb = PyCFunction_New(&updater_def, cap);
  Py_DECREF(cap);  // cb owns it now
  if (!cb) {
    return fail_py("updater trampoline failed");
  }
  PyObject* r = call_bridge("kv_set_updater",
                            Py_BuildValue("(ON)", h->obj, cb));
  if (!r) return fail_py("set updater failed");
  Py_DECREF(r);
  return 0;
}

int MXKVStoreBarrier(KVStoreHandle kv) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_barrier", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("barrier failed");
  Py_DECREF(r);
  return 0;
}

static int kv_str_call(const char* fn, KVStoreHandle kv, mx_uint num,
                       const char** keys, NDArrayHandle* vals,
                       int priority, int with_priority) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* args = PyTuple_New(with_priority ? 4 : 3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(num, keys));
  PyTuple_SET_ITEM(args, 2, nd_list(num, vals));
  if (with_priority)
    PyTuple_SET_ITEM(args, 3, PyLong_FromLong(priority));
  PyObject* r = call_bridge(fn, args);
  if (!r) return fail_py("kvstore string-key call failed");
  Py_DECREF(r);
  return 0;
}

int MXKVStoreInitEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* vals) {
  return kv_str_call("kv_init_str", kv, num, keys, vals, 0, 0);
}

int MXKVStorePushEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority) {
  return kv_str_call("kv_push_str", kv, num, keys, vals, priority, 1);
}

int MXKVStorePullEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority) {
  return kv_str_call("kv_pull_str", kv, num, keys, vals, priority, 1);
}

int MXKVStorePullRowSparse(KVStoreHandle kv, mx_uint num, const int* keys,
                           NDArrayHandle* vals,
                           const NDArrayHandle* row_ids, int priority) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* key_list = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SET_ITEM(key_list, i, PyLong_FromLong(keys[i]));
  PyObject* args = PyTuple_New(5);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, key_list);
  PyTuple_SET_ITEM(args, 2, nd_list(num, vals));
  PyTuple_SET_ITEM(
      args, 3,
      nd_list(num, const_cast<NDArrayHandle*>(row_ids)));
  PyTuple_SET_ITEM(args, 4, PyLong_FromLong(priority));
  PyObject* r = call_bridge("kv_pull_row_sparse", args);
  if (!r) return fail_py("pull row sparse failed");
  Py_DECREF(r);
  return 0;
}

static int role_predicate(const char* fn, int* ret) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge(fn, PyTuple_New(0));
  if (!r) return fail_py("role predicate failed");
  *ret = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreIsWorkerNode(int* ret) {
  return role_predicate("kv_is_worker_node", ret);
}

int MXKVStoreIsServerNode(int* ret) {
  return role_predicate("kv_is_server_node", ret);
}

int MXKVStoreIsSchedulerNode(int* ret) {
  return role_predicate("kv_is_scheduler_node", ret);
}

int MXKVStoreSendCommmandToServers(KVStoreHandle kv, int cmd_id,
                                   const char* cmd_body) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge(
      "kv_send_command_to_servers",
      Py_BuildValue("(Ois)", h->obj, cmd_id, cmd_body));
  if (!r) return fail_py("send command failed");
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetType(KVStoreHandle kv, const char** type) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_type", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("kv type failed");
  const char* s = utf8_or_null(r);
  if (!s) {
    Py_DECREF(r);
    return fail("non-UTF8 kv type");
  }
  ExtTLS* e = ext_tls();
  e->kv_type = s;
  Py_DECREF(r);
  *type = e->kv_type.c_str();
  return 0;
}

// -- RecordIO ----------------------------------------------------------

static int recordio_create(const char* bridge_name, const char* uri,
                           RecordIOHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge(bridge_name, Py_BuildValue("(s)", uri));
  if (!r) return fail_py("recordio create failed");
  *out = wrap_py(r);
  return 0;
}

static int recordio_free(RecordIOHandle handle) {
  if (!handle) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("recordio_close", Py_BuildValue("(O)", h->obj));
  Py_XDECREF(r);
  Py_XDECREF(h->obj);
  delete h;
  return r ? 0 : fail_py("recordio close failed");
}

int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out) {
  return recordio_create("recordio_writer_create", uri, out);
}

int MXRecordIOWriterFree(RecordIOHandle handle) {
  return recordio_free(handle);
}

int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char* buf,
                                size_t size) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* bytes = PyBytes_FromStringAndSize(buf, size);
  PyObject* r = call_bridge("recordio_write_record",
                            Py_BuildValue("(ON)", h->obj, bytes));
  if (!r) return fail_py("write record failed");
  Py_DECREF(r);
  return 0;
}

int MXRecordIOWriterTell(RecordIOHandle handle, size_t* pos) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r =
      call_bridge("recordio_writer_tell", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("writer tell failed");
  *pos = PyLong_AsSize_t(r);
  Py_DECREF(r);
  return 0;
}

int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out) {
  return recordio_create("recordio_reader_create", uri, out);
}

int MXRecordIOReaderFree(RecordIOHandle handle) {
  return recordio_free(handle);
}

int MXRecordIOReaderReadRecord(RecordIOHandle handle, const char** buf,
                               size_t* size) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r =
      call_bridge("recordio_read_record", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("read record failed");
  if (r == Py_None) {
    Py_DECREF(r);
    *buf = nullptr;
    *size = 0;
    return 0;
  }
  char* data = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &data, &len) != 0) {
    Py_DECREF(r);
    return fail_py("record not bytes");
  }
  ExtTLS* e = ext_tls();
  e->record_buf.assign(data, static_cast<size_t>(len));
  Py_DECREF(r);
  *buf = e->record_buf.data();
  *size = e->record_buf.size();
  return 0;
}

int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("recordio_reader_seek",
                            Py_BuildValue("(On)", h->obj,
                                          static_cast<Py_ssize_t>(pos)));
  if (!r) return fail_py("seek failed");
  Py_DECREF(r);
  return 0;
}

int MXRecordIOReaderTell(RecordIOHandle handle, size_t* pos) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r =
      call_bridge("recordio_reader_tell", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("reader tell failed");
  *pos = PyLong_AsSize_t(r);
  Py_DECREF(r);
  return 0;
}

// -- Profiler ----------------------------------------------------------

int MXSetProcessProfilerConfig(int num_params, const char* const* keys,
                               const char* const* vals,
                               KVStoreHandle kv_handle) {
  (void)kv_handle;
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0,
                   str_list(num_params, const_cast<const char**>(keys)));
  PyTuple_SET_ITEM(args, 1,
                   str_list(num_params, const_cast<const char**>(vals)));
  PyObject* r = call_bridge("profiler_set_config", args);
  if (!r) return fail_py("profiler config failed");
  Py_DECREF(r);
  return 0;
}

int MXSetProfilerConfig(int num_params, const char* const* keys,
                        const char* const* vals) {
  return MXSetProcessProfilerConfig(num_params, keys, vals, nullptr);
}

int MXSetProcessProfilerState(int state, int profile_process,
                              KVStoreHandle kv_handle) {
  (void)profile_process;
  (void)kv_handle;
  ensure_python();
  Gil gil;
  PyObject* r =
      call_bridge("profiler_set_state", Py_BuildValue("(i)", state));
  if (!r) return fail_py("profiler state failed");
  Py_DECREF(r);
  return 0;
}

int MXSetProfilerState(int state) {
  return MXSetProcessProfilerState(state, 0, nullptr);
}

int MXDumpProcessProfile(int finished, int profile_process,
                         KVStoreHandle kv_handle) {
  (void)profile_process;
  (void)kv_handle;
  ensure_python();
  Gil gil;
  PyObject* r =
      call_bridge("profiler_dump", Py_BuildValue("(i)", finished));
  if (!r) return fail_py("profiler dump failed");
  Py_DECREF(r);
  return 0;
}

int MXDumpProfile(int finished) {
  return MXDumpProcessProfile(finished, 0, nullptr);
}

int MXAggregateProfileStatsPrint(const char** out_str, int reset) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("profiler_aggregate_stats",
                            Py_BuildValue("(i)", reset));
  if (!r) return fail_py("profiler stats failed");
  const char* s = utf8_or_null(r);
  ExtTLS* e = ext_tls();
  e->agg_stats = s ? s : "";
  Py_DECREF(r);
  *out_str = e->agg_stats.c_str();
  return 0;
}

int MXProcessProfilePause(int paused, int profile_process,
                          KVStoreHandle kv_handle) {
  (void)profile_process;
  (void)kv_handle;
  ensure_python();
  Gil gil;
  PyObject* r =
      call_bridge("profiler_pause", Py_BuildValue("(i)", paused));
  if (!r) return fail_py("profiler pause failed");
  Py_DECREF(r);
  return 0;
}

int MXProfilePause(int paused) {
  return MXProcessProfilePause(paused, 0, nullptr);
}

}  // extern "C"

// ---------------------------------------------------------------------
// Symbol extras, executor monitor, autograd extras, runtime misc.
// ---------------------------------------------------------------------

extern "C" {

int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                      const char** keys, const int* arg_type_data,
                      mx_uint* in_type_size, const int** in_type_data,
                      mx_uint* out_type_size, const int** out_type_data,
                      mx_uint* aux_type_size, const int** aux_type_data,
                      int* complete) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* codes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SET_ITEM(codes, i, PyLong_FromLong(arg_type_data[i]));
  PyObject* args = PyTuple_New(3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(keys ? num_args : 0, keys));
  PyTuple_SET_ITEM(args, 2, codes);
  PyObject* r = call_bridge("symbol_infer_type", args);
  if (!r) return fail_py("infer type failed");
  // (arg_codes, out_codes, aux_codes, complete)
  static thread_local std::vector<int> stores[3];
  const int* outs[3];
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GET_ITEM(r, g);
    stores[g].clear();
    Py_ssize_t n = PyList_Size(lst);
    for (Py_ssize_t i = 0; i < n; ++i)
      stores[g].push_back(
          static_cast<int>(PyLong_AsLong(PyList_GET_ITEM(lst, i))));
    outs[g] = stores[g].data();
  }
  *complete = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 3)));
  Py_DECREF(r);
  *in_type_size = static_cast<mx_uint>(stores[0].size());
  *in_type_data = outs[0];
  *out_type_size = static_cast<mx_uint>(stores[1].size());
  *out_type_data = outs[1];
  *aux_type_size = static_cast<mx_uint>(stores[2].size());
  *aux_type_data = outs[2];
  return 0;
}

int MXSymbolGetAttr(SymbolHandle sym, const char* key, const char** out,
                    int* success) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_get_attr",
                            Py_BuildValue("(Os)", h->obj, key));
  if (!r) return fail_py("get attr failed");
  if (r == Py_None) {
    Py_DECREF(r);
    *out = nullptr;
    *success = 0;
    return 0;
  }
  const char* s = utf8_or_null(r);
  if (!s) {
    Py_DECREF(r);
    return fail("non-UTF8 attr value");
  }
  ExtTLS* e = ext_tls();
  e->attr_value = s;
  Py_DECREF(r);
  *out = e->attr_value.c_str();
  *success = 1;
  return 0;
}

int MXSymbolSetAttr(SymbolHandle sym, const char* key, const char* value) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_set_attr",
                            Py_BuildValue("(Oss)", h->obj, key, value));
  if (!r) return fail_py("set attr failed");
  Py_DECREF(r);
  return 0;
}

int MXSymbolListAttr(SymbolHandle sym, mx_uint* out_size,
                     const char*** out) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r =
      call_bridge("symbol_list_attr", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("list attr failed");
  return return_str_list(r, out_size, out);
}

int MXSymbolCopy(SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_copy", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("copy failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle* out) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r =
      call_bridge("symbol_get_internals", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("get internals failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolGetOutput(SymbolHandle sym, mx_uint index, SymbolHandle* out) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_get_output",
                            Py_BuildValue("(OI)", h->obj, index));
  if (!r) return fail_py("get output failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolGetNumOutputs(SymbolHandle sym, mx_uint* out) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r =
      call_bridge("symbol_num_outputs", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("num outputs failed");
  *out = static_cast<mx_uint>(PyLong_AsUnsignedLong(r));
  Py_DECREF(r);
  return 0;
}

int MXSymbolSaveToFile(SymbolHandle sym, const char* fname) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_save_file",
                            Py_BuildValue("(Os)", h->obj, fname));
  if (!r) return fail_py("save to file failed");
  Py_DECREF(r);
  return 0;
}

int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r =
      call_bridge("symbol_load_file", Py_BuildValue("(s)", fname));
  if (!r) return fail_py("load from file failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolListAtomicSymbolCreators(mx_uint* out_size,
                                     AtomicSymbolCreator** out_array) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("op_names_sorted", PyTuple_New(0));
  if (!r) return fail_py("op list failed");
  ExtTLS* e = ext_tls();
  // a creator is 1 + the op's index in the sorted name list (0 would be
  // indistinguishable from NULL); names cache alongside so the
  // per-creator lookups a codegen loop makes stay O(1)
  Py_ssize_t n = PyList_Size(r);
  e->creators.clear();
  e->creator_names.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = utf8_or_null(PyList_GET_ITEM(r, i));
    if (!s) {
      Py_DECREF(r);
      return fail("non-UTF8 op name");
    }
    e->creators.push_back(reinterpret_cast<void*>(i + 1));
    e->creator_names.push_back(s);
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(n);
  *out_array = e->creators.data();
  return 0;
}

static PyObject* creator_name(AtomicSymbolCreator creator) {
  // serve from the cache filled by ListAtomicSymbolCreators (stable:
  // the registry is append-only and the list is sorted); fill it on
  // first use for callers that skipped the List call
  ExtTLS* e = ext_tls();
  if (e->creator_names.empty()) {
    mx_uint n = 0;
    AtomicSymbolCreator* unused = nullptr;
    if (MXSymbolListAtomicSymbolCreators(&n, &unused) != 0)
      return nullptr;
  }
  size_t idx = reinterpret_cast<size_t>(creator) - 1;
  if (idx >= e->creator_names.size()) {
    PyErr_SetString(PyExc_IndexError, "bad AtomicSymbolCreator");
    return nullptr;
  }
  return PyUnicode_FromString(e->creator_names[idx].c_str());
}

int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char** name) {
  ensure_python();
  Gil gil;
  PyObject* n = creator_name(creator);
  if (!n) return fail_py("creator name failed");
  const char* s = utf8_or_null(n);
  if (!s) {
    Py_DECREF(n);
    return fail("non-UTF8 op name");
  }
  ExtTLS* e = ext_tls();
  e->op_name = s;
  Py_DECREF(n);
  *name = e->op_name.c_str();
  return 0;
}

int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char** name, const char** description,
                                mx_uint* num_args, const char*** arg_names,
                                const char*** arg_type_infos,
                                const char*** arg_descriptions,
                                const char** key_var_num_args,
                                const char** return_type) {
  ensure_python();
  Gil gil;
  PyObject* n = creator_name(creator);
  if (!n) return fail_py("creator name failed");
  PyObject* r = call_bridge("op_info", Py_BuildValue("(N)", n));
  if (!r) return fail_py("op info failed");
  // (name, doc, arg_names, arg_types, arg_descs, return_type)
  ExtTLS* e = ext_tls();
  e->op_name = safe_utf8(PyTuple_GET_ITEM(r, 0));
  e->op_desc = safe_utf8(PyTuple_GET_ITEM(r, 1));
  e->op_doc_store.clear();
  for (int g = 0; g < 3; ++g) e->op_doc_ptrs[g].clear();
  PyObject* groups[3] = {PyTuple_GET_ITEM(r, 2), PyTuple_GET_ITEM(r, 3),
                         PyTuple_GET_ITEM(r, 4)};
  // collect all strings first (vector growth would invalidate c_str())
  std::vector<size_t> counts;
  for (int g = 0; g < 3; ++g) {
    Py_ssize_t cnt = PyList_Size(groups[g]);
    counts.push_back(static_cast<size_t>(cnt));
    for (Py_ssize_t i = 0; i < cnt; ++i)
      e->op_doc_store.push_back(safe_utf8(PyList_GET_ITEM(groups[g], i)));
  }
  size_t off = 0;
  for (int g = 0; g < 3; ++g) {
    for (size_t i = 0; i < counts[g]; ++i)
      e->op_doc_ptrs[g].push_back(e->op_doc_store[off + i].c_str());
    off += counts[g];
  }
  static const char* kEmpty = "";
  static thread_local std::string ret_type_store;
  ret_type_store = safe_utf8(PyTuple_GET_ITEM(r, 5));
  Py_DECREF(r);
  *name = e->op_name.c_str();
  *description = e->op_desc.c_str();
  *num_args = static_cast<mx_uint>(counts[0]);
  *arg_names = e->op_doc_ptrs[0].data();
  *arg_type_infos = e->op_doc_ptrs[1].data();
  *arg_descriptions = e->op_doc_ptrs[2].data();
  if (key_var_num_args) *key_var_num_args = kEmpty;
  if (return_type) *return_type = ret_type_store.c_str();
  return 0;
}

// -- Executor monitor --------------------------------------------------

int MXExecutorSetMonitorCallback(ExecutorHandle ex,
                                 ExecutorMonitorCallback callback,
                                 void* callback_handle) {
  return MXExecutorSetMonitorCallbackEX(ex, callback, callback_handle, 0);
}

int MXExecutorSetMonitorCallbackEX(ExecutorHandle ex,
                                   ExecutorMonitorCallback callback,
                                   void* callback_handle, int monitor_all) {
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  auto* ctx = new MonitorCtx{callback, callback_handle};
  PyObject* cap =
      PyCapsule_New(ctx, "mxtpu.monitor", capsule_free_monitor);
  PyObject* cb = PyCFunction_New(&monitor_def, cap);
  Py_DECREF(cap);
  if (!cb) return fail_py("monitor trampoline failed");
  PyObject* r = call_bridge(
      "executor_set_monitor",
      Py_BuildValue("(ONi)", h->obj, cb, monitor_all));
  if (!r) return fail_py("set monitor failed");
  Py_DECREF(r);
  return 0;
}

// -- Autograd extras ---------------------------------------------------

int MXAutogradIsRecording(unsigned char* curr) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("autograd_is_recording", PyTuple_New(0));
  if (!r) return fail_py("is recording failed");
  *curr = static_cast<unsigned char>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradIsTraining(unsigned char* curr) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("autograd_is_training", PyTuple_New(0));
  if (!r) return fail_py("is training failed");
  *curr = static_cast<unsigned char>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles, mx_uint num_variables,
                         NDArrayHandle* var_handles, int retain_graph,
                         int create_graph, int is_train,
                         NDArrayHandle** grad_handles,
                         const int** grad_stypes) {
  Gil gil;
  PyObject* args = PyTuple_New(6);
  PyTuple_SET_ITEM(args, 0, nd_list(num_output, output_handles));
  if (ograd_handles) {
    PyTuple_SET_ITEM(args, 1, nd_list(num_output, ograd_handles));
  } else {
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(args, 1, Py_None);
  }
  PyTuple_SET_ITEM(args, 2, nd_list(num_variables, var_handles));
  PyTuple_SET_ITEM(args, 3, PyBool_FromLong(retain_graph));
  PyTuple_SET_ITEM(args, 4, PyBool_FromLong(create_graph));
  PyTuple_SET_ITEM(args, 5, PyBool_FromLong(is_train));
  PyObject* r = call_bridge("autograd_backward_ex", args);
  if (!r) return fail_py("backward failed");
  ExtTLS* e = ext_tls();
  e->grad_out.clear();
  e->grad_stypes.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(r, i);
    Py_INCREF(a);
    e->grad_out.push_back(wrap(a));
    e->grad_stypes.push_back(1);  // dense-backed
  }
  Py_DECREF(r);
  if (grad_handles) *grad_handles = e->grad_out.data();
  if (grad_stypes) *grad_stypes = e->grad_stypes.data();
  return 0;
}

// -- Runtime misc ------------------------------------------------------

int MXGetVersion(int* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("get_version", PyTuple_New(0));
  if (!r) return fail_py("version failed");
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXRandomSeed(int seed) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("random_seed", Py_BuildValue("(i)", seed));
  if (!r) return fail_py("seed failed");
  Py_DECREF(r);
  return 0;
}

int MXRandomSeedContext(int seed, int dev_type, int dev_id) {
  (void)dev_type;
  (void)dev_id;  // one RNG stream serves every device (jax key model)
  return MXRandomSeed(seed);
}

int MXGetGPUCount(int* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("device_count", PyTuple_New(0));
  if (!r) return fail_py("device count failed");
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}


/* =====================================================================
 * Round-4 completion planes (see c_api.h) — same bridge conventions.
 * ===================================================================== */

// ---- symbol extras ---------------------------------------------------

int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle* symbols,
                        SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* lst = PyList_New(num_symbols);
  for (mx_uint i = 0; i < num_symbols; ++i) {
    PyObject* o = static_cast<PyHandle*>(symbols[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(lst, i, o);
  }
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, lst);
  PyObject* r = call_bridge("symbol_create_group", args);
  if (!r) return fail_py("create group failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolGetName(SymbolHandle sym, const char** out, int* success) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_get_name",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("get name failed");
  // r = (name-or-None, success)
  PyObject* name = PyTuple_GET_ITEM(r, 0);
  *success = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 1)));
  ExtTLS* e = ext_tls();
  e->attr_value = (*success && name != Py_None) ? safe_utf8(name) : "";
  Py_DECREF(r);
  *out = e->attr_value.c_str();
  return 0;
}

int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_get_children",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("get children failed");
  if (r == Py_None) {
    Py_DECREF(r);
    *out = nullptr;
    return 0;
  }
  *out = wrap_py(r);
  return 0;
}

int MXSymbolGetInputSymbols(SymbolHandle sym, SymbolHandle** inputs,
                            int* input_size) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_get_input_symbols",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("get input symbols failed");
  static thread_local std::vector<SymbolHandle> store;
  store.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* s = PyList_GET_ITEM(r, i);
    Py_INCREF(s);
    store.push_back(wrap_py(s));
  }
  Py_DECREF(r);
  *inputs = store.data();
  *input_size = static_cast<int>(n);
  return 0;
}

int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt, const char** wrt,
                 SymbolHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* args = PyTuple_New(2);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(num_wrt, wrt));
  PyObject* r = call_bridge("symbol_grad", args);
  if (!r) return fail_py("symbol grad failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolInferShapePartial(
    SymbolHandle sym, mx_uint num_args, const char** keys,
    const mx_uint* arg_ind_ptr, const mx_uint* arg_shape_data,
    mx_uint* in_shape_size, const mx_uint** in_shape_ndim,
    const mx_uint*** in_shape_data, mx_uint* out_shape_size,
    const mx_uint** out_shape_ndim, const mx_uint*** out_shape_data,
    mx_uint* aux_shape_size, const mx_uint** aux_shape_ndim,
    const mx_uint*** aux_shape_data, int* complete) {
  // the shape-inference bridge is already partial (unknown -> ndim 0)
  return MXSymbolInferShape(sym, num_args, keys, arg_ind_ptr,
                            arg_shape_data, in_shape_size, in_shape_ndim,
                            in_shape_data, out_shape_size, out_shape_ndim,
                            out_shape_data, aux_shape_size, aux_shape_ndim,
                            aux_shape_data, complete);
}

int MXSymbolInferTypePartial(SymbolHandle sym, mx_uint num_args,
                             const char** keys, const int* arg_type_data,
                             mx_uint* in_type_size, const int** in_type_data,
                             mx_uint* out_type_size,
                             const int** out_type_data,
                             mx_uint* aux_type_size,
                             const int** aux_type_data, int* complete) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* codes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SET_ITEM(codes, i, PyLong_FromLong(arg_type_data[i]));
  PyObject* args = PyTuple_New(3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(keys ? num_args : 0, keys));
  PyTuple_SET_ITEM(args, 2, codes);
  PyObject* r = call_bridge("symbol_infer_type_partial", args);
  if (!r) return fail_py("infer type partial failed");
  static thread_local std::vector<int> stores[3];
  const int* outs[3];
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GET_ITEM(r, g);
    stores[g].clear();
    Py_ssize_t n = PyList_Size(lst);
    for (Py_ssize_t i = 0; i < n; ++i)
      stores[g].push_back(
          static_cast<int>(PyLong_AsLong(PyList_GET_ITEM(lst, i))));
    outs[g] = stores[g].data();
  }
  *complete = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 3)));
  Py_DECREF(r);
  *in_type_size = static_cast<mx_uint>(stores[0].size());
  *in_type_data = outs[0];
  *out_type_size = static_cast<mx_uint>(stores[1].size());
  *out_type_data = outs[1];
  *aux_type_size = static_cast<mx_uint>(stores[2].size());
  *aux_type_data = outs[2];
  return 0;
}

int MXSymbolListAttrShallow(SymbolHandle sym, mx_uint* out_size,
                            const char*** out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_list_attr_shallow",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("list attr shallow failed");
  return return_str_list(r, out_size, out);
}

int MXSymbolPrint(SymbolHandle sym, const char** out_str) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_print", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("symbol print failed");
  ExtTLS* e = ext_tls();
  e->attr_value = safe_utf8(r);
  Py_DECREF(r);
  *out_str = e->attr_value.c_str();
  return 0;
}

int MXSymbolCutSubgraph(SymbolHandle sym, SymbolHandle** inputs,
                        int* input_size) {
  (void)sym;
  *inputs = nullptr;
  *input_size = 0;  // control-flow subgraphs are explicit attributes here
  return 0;
}

// ---- executor extras -------------------------------------------------

namespace {

// shared result unpacking for simple-bind/reshape: r = (executor,
// args, grads, auxs); fills thread-local handle arrays
int unpack_bind_result(PyObject* r, mx_uint* num_in_args,
                       NDArrayHandle** in_args, NDArrayHandle** arg_grads,
                       mx_uint* num_aux_states, NDArrayHandle** aux_states,
                       ExecutorHandle* out) {
  static thread_local std::vector<NDArrayHandle> args_store, grads_store,
      aux_store;
  args_store.clear();
  grads_store.clear();
  aux_store.clear();
  PyObject* ex = PyTuple_GET_ITEM(r, 0);
  PyObject* argl = PyTuple_GET_ITEM(r, 1);
  PyObject* gradl = PyTuple_GET_ITEM(r, 2);
  PyObject* auxl = PyTuple_GET_ITEM(r, 3);
  for (Py_ssize_t i = 0; i < PyList_Size(argl); ++i) {
    PyObject* a = PyList_GET_ITEM(argl, i);
    Py_INCREF(a);
    args_store.push_back(wrap(a));
  }
  for (Py_ssize_t i = 0; i < PyList_Size(gradl); ++i) {
    PyObject* g = PyList_GET_ITEM(gradl, i);
    if (g == Py_None) {
      grads_store.push_back(nullptr);
    } else {
      Py_INCREF(g);
      grads_store.push_back(wrap(g));
    }
  }
  for (Py_ssize_t i = 0; i < PyList_Size(auxl); ++i) {
    PyObject* a = PyList_GET_ITEM(auxl, i);
    Py_INCREF(a);
    aux_store.push_back(wrap(a));
  }
  Py_INCREF(ex);
  Py_DECREF(r);
  *num_in_args = static_cast<mx_uint>(args_store.size());
  *in_args = args_store.data();
  *arg_grads = grads_store.data();
  *num_aux_states = static_cast<mx_uint>(aux_store.size());
  *aux_states = aux_store.data();
  *out = wrap_py(ex);
  return 0;
}

PyObject* shape_csr_args(mx_uint num, const char** names,
                         const mx_uint* ind_ptr, const mx_uint* data,
                         PyObject** ndims_out, PyObject** flat_out) {
  PyObject* keys = str_list(num, names);
  PyObject* ndims = PyList_New(num);
  mx_uint total = num ? ind_ptr[num] : 0;
  PyObject* flat = PyList_New(total);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SET_ITEM(ndims, i, PyLong_FromUnsignedLong(
        ind_ptr[i + 1] - ind_ptr[i]));
  for (mx_uint i = 0; i < total; ++i)
    PyList_SET_ITEM(flat, i, PyLong_FromUnsignedLong(data[i]));
  *ndims_out = ndims;
  *flat_out = flat;
  return keys;
}

}  // namespace

int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         mx_uint grad_req_type, mx_uint num_provided_args,
                         const char** provided_arg_shape_names,
                         const mx_uint* provided_arg_shape_ind_ptr,
                         const mx_uint* provided_arg_shape_data,
                         mx_uint* num_in_args, NDArrayHandle** in_args,
                         NDArrayHandle** arg_grads, mx_uint* num_aux_states,
                         NDArrayHandle** aux_states, ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject *ndims, *flat;
  PyObject* keys = shape_csr_args(num_provided_args,
                                  provided_arg_shape_names,
                                  provided_arg_shape_ind_ptr,
                                  provided_arg_shape_data, &ndims, &flat);
  PyObject* args = PyTuple_New(7);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(dev_type));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(dev_id));
  PyTuple_SET_ITEM(args, 3, PyLong_FromUnsignedLong(grad_req_type));
  PyTuple_SET_ITEM(args, 4, keys);
  PyTuple_SET_ITEM(args, 5, ndims);
  PyTuple_SET_ITEM(args, 6, flat);
  PyObject* r = call_bridge("executor_simple_bind", args);
  if (!r) return fail_py("simple bind failed");
  return unpack_bind_result(r, num_in_args, in_args, arg_grads,
                            num_aux_states, aux_states, out);
}

int MXExecutorReshape(int partial_shaping, int allow_up_sizing,
                      ExecutorHandle ex, mx_uint num_provided_args,
                      const char** provided_arg_shape_names,
                      const mx_uint* provided_arg_shape_ind_ptr,
                      const mx_uint* provided_arg_shape_data,
                      mx_uint* num_in_args, NDArrayHandle** in_args,
                      NDArrayHandle** arg_grads, mx_uint* num_aux_states,
                      NDArrayHandle** aux_states, ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject *ndims, *flat;
  PyObject* keys = shape_csr_args(num_provided_args,
                                  provided_arg_shape_names,
                                  provided_arg_shape_ind_ptr,
                                  provided_arg_shape_data, &ndims, &flat);
  PyObject* args = PyTuple_New(6);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(partial_shaping));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(allow_up_sizing));
  PyTuple_SET_ITEM(args, 3, keys);
  PyTuple_SET_ITEM(args, 4, ndims);
  PyTuple_SET_ITEM(args, 5, flat);
  PyObject* r = call_bridge("executor_reshape", args);
  if (!r) return fail_py("executor reshape failed");
  return unpack_bind_result(r, num_in_args, in_args, arg_grads,
                            num_aux_states, aux_states, out);
}

int MXExecutorPrint(ExecutorHandle ex, const char** out_str) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* r = call_bridge("executor_print", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("executor print failed");
  ExtTLS* e = ext_tls();
  e->attr_value = safe_utf8(r);
  Py_DECREF(r);
  *out_str = e->attr_value.c_str();
  return 0;
}

int MXExecutorBackwardEx(ExecutorHandle ex, mx_uint len,
                         NDArrayHandle* head_grads, int is_train) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* args = PyTuple_New(3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, nd_list(len, head_grads));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(is_train));
  PyObject* r = call_bridge("executor_backward_ex", args);
  if (!r) return fail_py("executor backward ex failed");
  Py_DECREF(r);
  return 0;
}

namespace {

int bind_x_impl(SymbolHandle sym, int dev_type, int dev_id,
                mx_uint num_map_keys, const char** map_keys,
                const int* map_dev_types, const int* map_dev_ids,
                mx_uint num_args, NDArrayHandle* in_args,
                NDArrayHandle* arg_grad_store,
                const mx_uint* grad_req_type, mx_uint aux_states_len,
                NDArrayHandle* aux_states, ExecutorHandle shared_exec,
                ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* map_types = PyList_New(num_map_keys);
  PyObject* map_ids = PyList_New(num_map_keys);
  for (mx_uint i = 0; i < num_map_keys; ++i) {
    PyList_SET_ITEM(map_types, i, PyLong_FromLong(map_dev_types[i]));
    PyList_SET_ITEM(map_ids, i, PyLong_FromLong(map_dev_ids[i]));
  }
  PyObject* reqs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SET_ITEM(reqs, i, PyLong_FromUnsignedLong(grad_req_type[i]));
  PyObject* args = PyTuple_New(11);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(dev_type));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(dev_id));
  PyTuple_SET_ITEM(args, 3, str_list(num_map_keys, map_keys));
  PyTuple_SET_ITEM(args, 4, map_types);
  PyTuple_SET_ITEM(args, 5, map_ids);
  PyTuple_SET_ITEM(args, 6, nd_list(num_args, in_args));
  PyTuple_SET_ITEM(args, 7, nd_list(num_args, arg_grad_store));
  PyTuple_SET_ITEM(args, 8, reqs);
  PyTuple_SET_ITEM(args, 9, nd_list(aux_states_len, aux_states));
  if (shared_exec) {
    PyObject* se = static_cast<PyHandle*>(shared_exec)->obj;
    Py_INCREF(se);
    PyTuple_SET_ITEM(args, 10, se);
  } else {
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(args, 10, Py_None);
  }
  PyObject* r = call_bridge("executor_bind_x", args);
  if (!r) return fail_py("executor bind x failed");
  *out = wrap_py(r);
  return 0;
}

}  // namespace

int MXExecutorBindX(SymbolHandle sym, int dev_type, int dev_id,
                    mx_uint num_map_keys, const char** map_keys,
                    const int* map_dev_types, const int* map_dev_ids,
                    mx_uint num_args, NDArrayHandle* in_args,
                    NDArrayHandle* arg_grad_store,
                    const mx_uint* grad_req_type, mx_uint aux_states_len,
                    NDArrayHandle* aux_states, ExecutorHandle* out) {
  return bind_x_impl(sym, dev_type, dev_id, num_map_keys, map_keys,
                     map_dev_types, map_dev_ids, num_args, in_args,
                     arg_grad_store, grad_req_type, aux_states_len,
                     aux_states, nullptr, out);
}

int MXExecutorBindEX(SymbolHandle sym, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char** map_keys,
                     const int* map_dev_types, const int* map_dev_ids,
                     mx_uint num_args, NDArrayHandle* in_args,
                     NDArrayHandle* arg_grad_store,
                     const mx_uint* grad_req_type, mx_uint aux_states_len,
                     NDArrayHandle* aux_states, ExecutorHandle shared_exec,
                     ExecutorHandle* out) {
  return bind_x_impl(sym, dev_type, dev_id, num_map_keys, map_keys,
                     map_dev_types, map_dev_ids, num_args, in_args,
                     arg_grad_store, grad_req_type, aux_states_len,
                     aux_states, shared_exec, out);
}

int MXExecutorGetOptimizedSymbol(ExecutorHandle ex, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* r = call_bridge("executor_optimized_symbol",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("optimized symbol failed");
  *out = wrap_py(r);
  return 0;
}

// ---- KVStore extras --------------------------------------------------

int MXKVStorePullRowSparseEx(KVStoreHandle kv, mx_uint num,
                             const char** keys, NDArrayHandle* vals,
                             const NDArrayHandle* row_ids, int priority) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* args = PyTuple_New(5);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(num, keys));
  PyTuple_SET_ITEM(args, 2, nd_list(num, vals));
  PyTuple_SET_ITEM(args, 3,
                   nd_list(num, const_cast<NDArrayHandle*>(row_ids)));
  PyTuple_SET_ITEM(args, 4, PyLong_FromLong(priority));
  PyObject* r = call_bridge("kv_pull_row_sparse_str", args);
  if (!r) return fail_py("pull row sparse failed");
  Py_DECREF(r);
  return 0;
}

namespace {

int kv_pull_sparse_impl(KVStoreHandle kv, PyObject* keys,
                        mx_uint num, NDArrayHandle* vals, int priority,
                        unsigned char ignore_sparse) {
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* args = PyTuple_New(5);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, keys);
  PyTuple_SET_ITEM(args, 2, nd_list(num, vals));
  PyTuple_SET_ITEM(args, 3, PyLong_FromLong(priority));
  PyTuple_SET_ITEM(args, 4, PyLong_FromLong(ignore_sparse));
  PyObject* r = call_bridge("kv_pull_with_sparse", args);
  if (!r) return fail_py("pull with sparse failed");
  Py_DECREF(r);
  return 0;
}

}  // namespace

int MXKVStorePullWithSparse(KVStoreHandle kv, mx_uint num, const int* keys,
                            NDArrayHandle* vals, int priority,
                            unsigned char ignore_sparse) {
  ensure_python();
  Gil gil;
  PyObject* key_list = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SET_ITEM(key_list, i, PyLong_FromLong(keys[i]));
  return kv_pull_sparse_impl(kv, key_list, num, vals, priority,
                             ignore_sparse);
}

int MXKVStorePullWithSparseEx(KVStoreHandle kv, mx_uint num,
                              const char** keys, NDArrayHandle* vals,
                              int priority, unsigned char ignore_sparse) {
  ensure_python();
  Gil gil;
  return kv_pull_sparse_impl(kv, str_list(num, keys), num, vals, priority,
                             ignore_sparse);
}

int MXKVStoreSetGradientCompression(KVStoreHandle kv, mx_uint num_params,
                                    const char** keys, const char** vals) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* args = PyTuple_New(3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(num_params, keys));
  PyTuple_SET_ITEM(args, 2, str_list(num_params, vals));
  PyObject* r = call_bridge("kv_set_gradient_compression", args);
  if (!r) return fail_py("set gradient compression failed");
  Py_DECREF(r);
  return 0;
}

int MXKVStoreRunServer(KVStoreHandle kv, MXKVStoreServerController controller,
                       void* controller_handle) {
  (void)controller;
  (void)controller_handle;  // in-process server: no controller loop to run
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_run_server", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("run server failed");
  Py_DECREF(r);
  return 0;
}

int MXKVStoreSetBarrierBeforeExit(KVStoreHandle kv, int do_barrier) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_set_barrier_before_exit",
                            Py_BuildValue("(Oi)", h->obj, do_barrier));
  if (!r) return fail_py("set barrier before exit failed");
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetNumDeadNode(KVStoreHandle kv, int node_id, int* number) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_num_dead_node",
                            Py_BuildValue("(Oi)", h->obj, node_id));
  if (!r) return fail_py("num dead node failed");
  *number = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXInitPSEnv(mx_uint num_vars, const char** keys, const char** vals) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, str_list(num_vars, keys));
  PyTuple_SET_ITEM(args, 1, str_list(num_vars, vals));
  PyObject* r = call_bridge("init_ps_env", args);
  if (!r) return fail_py("init ps env failed");
  Py_DECREF(r);
  return 0;
}

// ---- NDArray extras --------------------------------------------------

int MXNDArrayGetData(NDArrayHandle handle, void** out_pdata) {
  ensure_python();
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* fn = bridge_fn("to_bytes");
  if (!fn) return fail_py("bridge missing");
  PyObject* bytes = PyObject_CallFunction(fn, "O", obj->array);
  Py_DECREF(fn);
  if (!bytes) return fail_py("data sync failed");
  // host mirror lives on the handle, valid until the next call on it
  obj->host_data.assign(PyBytes_AsString(bytes),
                        PyBytes_AsString(bytes) + PyBytes_Size(bytes));
  Py_DECREF(bytes);
  *out_pdata = obj->host_data.data();
  return 0;
}

int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  return MXNDArrayWaitToRead(handle);
}

int MXNDArraySyncCopyFromNDArray(NDArrayHandle handle_dst,
                                 const NDArrayHandle handle_src, int i) {
  ensure_python();
  Gil gil;
  auto* dst = static_cast<NDArrayObj*>(handle_dst);
  auto* src = static_cast<NDArrayObj*>(const_cast<void*>(handle_src));
  PyObject* r = call_bridge(
      "nd_sync_copy_from_ndarray",
      Py_BuildValue("(OOi)", dst->array, src->array, i));
  if (!r) return fail_py("sync copy from ndarray failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoadFromBuffer(const void* ndarray_buffer, size_t size,
                            mx_uint* out_size, NDArrayHandle** out_arr,
                            mx_uint* out_name_size,
                            const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* buf = PyBytes_FromStringAndSize(
      static_cast<const char*>(ndarray_buffer),
      static_cast<Py_ssize_t>(size));
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, buf);
  PyObject* r = call_bridge("nd_load_from_buffer", args);
  if (!r) return fail_py("load from buffer failed");
  // r = (arrays, names)
  TLS* t = tls();
  t->load_out.clear();
  t->load_str_store.clear();
  t->load_cstr_out.clear();
  PyObject* arrays = PyTuple_GET_ITEM(r, 0);
  PyObject* names = PyTuple_GET_ITEM(r, 1);
  for (Py_ssize_t i = 0; i < PyList_Size(arrays); ++i) {
    PyObject* a = PyList_GET_ITEM(arrays, i);
    Py_INCREF(a);
    t->load_out.push_back(wrap(a));
  }
  for (Py_ssize_t i = 0; i < PyList_Size(names); ++i) {
    const char* s = utf8_or_null(PyList_GET_ITEM(names, i));
    if (!s) {
      Py_DECREF(r);
      return fail("non-UTF8 name in buffer");
    }
    t->load_str_store.push_back(s);
  }
  Py_DECREF(r);
  for (auto& s : t->load_str_store)
    t->load_cstr_out.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(t->load_out.size());
  *out_arr = t->load_out.data();
  *out_name_size = static_cast<mx_uint>(t->load_cstr_out.size());
  *out_names = t->load_cstr_out.data();
  return 0;
}

int MXNDArraySyncCheckFormat(NDArrayHandle handle, const int full_check) {
  ensure_python();
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge(
      "nd_sync_check_format",
      Py_BuildValue("(Oi)", obj->array, full_check));
  if (!r) return fail_py("format check failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayCreateSparseEx(int storage_type, const mx_uint* shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, mx_uint num_aux,
                            int* aux_type, mx_uint* aux_ndims,
                            const mx_uint* aux_shape, NDArrayHandle* out) {
  (void)delay_alloc;  // XLA allocates on materialization anyway
  ensure_python();
  Gil gil;
  const char* stype = storage_type == 1 ? "row_sparse"
                      : storage_type == 2 ? "csr" : nullptr;
  if (!stype) return fail("storage_type must be 1 (row_sparse) or 2 (csr)");
  PyObject* shp = PyList_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyList_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* atypes = PyList_New(num_aux);
  PyObject* andims = PyList_New(num_aux);
  mx_uint aux_total = 0;
  for (mx_uint i = 0; i < num_aux; ++i) {
    PyList_SET_ITEM(atypes, i, PyLong_FromLong(aux_type ? aux_type[i] : 6));
    PyList_SET_ITEM(andims, i,
                    PyLong_FromUnsignedLong(aux_ndims ? aux_ndims[i] : 0));
    aux_total += aux_ndims ? aux_ndims[i] : 0;
  }
  PyObject* aflat = PyList_New(aux_total);
  for (mx_uint i = 0; i < aux_total; ++i)
    PyList_SET_ITEM(aflat, i,
                    PyLong_FromUnsignedLong(aux_shape ? aux_shape[i] : 0));
  PyObject* args = PyTuple_New(8);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(stype));
  PyTuple_SET_ITEM(args, 1, shp);
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(dev_type));
  PyTuple_SET_ITEM(args, 3, PyLong_FromLong(dev_id));
  PyTuple_SET_ITEM(args, 4, PyLong_FromLong(dtype));
  PyTuple_SET_ITEM(args, 5, atypes);
  PyTuple_SET_ITEM(args, 6, andims);
  PyTuple_SET_ITEM(args, 7, aflat);
  PyObject* r = call_bridge("nd_create_sparse", args);
  if (!r) return fail_py("create sparse failed");
  *out = wrap(r);
  return 0;
}

int MXNDArrayGetSharedMemHandle(NDArrayHandle handle, int* shared_pid,
                                int* shared_id) {
  (void)handle;
  (void)shared_pid;
  (void)shared_id;
  return fail(
      "shared-memory NDArrays are a CPU-engine IPC mechanism with no TPU "
      "analogue (device buffers are not shm-shareable; the DataLoader "
      "uses its own IPC)");
}

int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                 const mx_uint* shape, mx_uint ndim,
                                 int dtype, NDArrayHandle* out) {
  (void)shared_pid;
  (void)shared_id;
  (void)shape;
  (void)ndim;
  (void)dtype;
  (void)out;
  return fail(
      "shared-memory NDArrays are a CPU-engine IPC mechanism with no TPU "
      "analogue (device buffers are not shm-shareable; the DataLoader "
      "uses its own IPC)");
}

// ---- autograd / custom extras ----------------------------------------

int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle* output_handles) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, nd_list(num_output, output_handles));
  PyObject* r = call_bridge("autograd_compute_gradient", args);
  if (!r) return fail_py("compute gradient failed");
  Py_DECREF(r);
  return 0;
}

int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle* out) {
  (void)handle;
  (void)out;
  return fail(
      "the imperative tape records jax VJPs, not Symbol graphs; trace a "
      "HybridBlock and export it to obtain a serving graph");
}

int MXCustomOpRegister(const char* op_type, void* creator) {
  (void)op_type;
  (void)creator;
  return fail(
      "C-side custom ops are not supported; register custom operators in "
      "Python (mx.operator.register / autograd.Function) or as Pallas "
      "kernels (mx.rtc.PallasModule)");
}

int MXCustomFunctionRecord(int num_inputs, NDArrayHandle* inputs,
                           int num_outputs, NDArrayHandle* outputs,
                           void* callbacks) {
  (void)num_inputs;
  (void)inputs;
  (void)num_outputs;
  (void)outputs;
  (void)callbacks;
  return fail(
      "C-side custom autograd functions are not supported; use "
      "mx.autograd.Function in Python");
}

// ---- data-iter extras ------------------------------------------------

int MXDataIterGetIndex(DataIterHandle handle, uint64_t** out_index,
                       uint64_t* out_size) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("dataiter_get_index",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("get index failed");
  static thread_local std::vector<uint64_t> store;
  store.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i)
    store.push_back(PyLong_AsUnsignedLongLong(PyList_GET_ITEM(r, i)));
  Py_DECREF(r);
  *out_index = store.data();
  *out_size = static_cast<uint64_t>(n);
  return 0;
}

int MXDataIterGetIterInfo(const char* name, const char** out_name,
                          const char** description, mx_uint* num_args,
                          const char*** arg_names,
                          const char*** arg_type_infos,
                          const char*** arg_descriptions) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("dataiter_get_info",
                            Py_BuildValue("(s)", name));
  if (!r) return fail_py("get iter info failed");
  // r = (name, doc, names, types, descs)
  ExtTLS* e = ext_tls();
  e->op_name = safe_utf8(PyTuple_GET_ITEM(r, 0));
  e->op_desc = safe_utf8(PyTuple_GET_ITEM(r, 1));
  e->op_doc_store.clear();
  size_t counts[3];
  for (int g = 0; g < 3; ++g) {
    PyObject* lst = PyTuple_GET_ITEM(r, 2 + g);
    counts[g] = static_cast<size_t>(PyList_Size(lst));
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i)
      e->op_doc_store.push_back(safe_utf8(PyList_GET_ITEM(lst, i)));
  }
  Py_DECREF(r);
  size_t off = 0;
  for (int g = 0; g < 3; ++g) {
    e->op_doc_ptrs[g].clear();
    for (size_t i = 0; i < counts[g]; ++i)
      e->op_doc_ptrs[g].push_back(e->op_doc_store[off + i].c_str());
    off += counts[g];
  }
  *out_name = e->op_name.c_str();
  *description = e->op_desc.c_str();
  *num_args = static_cast<mx_uint>(counts[0]);
  *arg_names = e->op_doc_ptrs[0].data();
  *arg_type_infos = e->op_doc_ptrs[1].data();
  *arg_descriptions = e->op_doc_ptrs[2].data();
  return 0;
}

// ---- profile object ABI ----------------------------------------------

namespace {

int profile_create(const char* bridge_name, PyObject* args,
                   ProfileHandle* out) {
  PyObject* r = call_bridge(bridge_name, args);
  if (!r) return fail_py("profile create failed");
  *out = wrap_py(r);
  return 0;
}

}  // namespace

int MXProfileCreateDomain(const char* domain, ProfileHandle* out) {
  ensure_python();
  Gil gil;
  return profile_create("profile_create_domain",
                        Py_BuildValue("(s)", domain), out);
}

int MXProfileCreateTask(ProfileHandle domain, const char* task_name,
                        ProfileHandle* out) {
  ensure_python();
  Gil gil;
  auto* d = static_cast<PyHandle*>(domain);
  return profile_create("profile_create_task",
                        Py_BuildValue("(Os)", d->obj, task_name), out);
}

int MXProfileCreateFrame(ProfileHandle domain, const char* frame_name,
                         ProfileHandle* out) {
  ensure_python();
  Gil gil;
  auto* d = static_cast<PyHandle*>(domain);
  return profile_create("profile_create_frame",
                        Py_BuildValue("(Os)", d->obj, frame_name), out);
}

int MXProfileCreateEvent(const char* event_name, ProfileHandle* out) {
  ensure_python();
  Gil gil;
  return profile_create("profile_create_event",
                        Py_BuildValue("(s)", event_name), out);
}

int MXProfileCreateCounter(ProfileHandle domain, const char* counter_name,
                           ProfileHandle* out) {
  ensure_python();
  Gil gil;
  auto* d = static_cast<PyHandle*>(domain);
  return profile_create("profile_create_counter",
                        Py_BuildValue("(Os)", d->obj, counter_name), out);
}

int MXProfileDestroyHandle(ProfileHandle frame_handle) {
  if (!frame_handle) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(frame_handle);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXProfileDurationStart(ProfileHandle duration_handle) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(duration_handle);
  PyObject* r = call_bridge("profile_duration_start",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("duration start failed");
  Py_DECREF(r);
  return 0;
}

int MXProfileDurationStop(ProfileHandle duration_handle) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(duration_handle);
  PyObject* r = call_bridge("profile_duration_stop",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("duration stop failed");
  Py_DECREF(r);
  return 0;
}

int MXProfileSetCounter(ProfileHandle counter_handle, uint64_t value) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(counter_handle);
  PyObject* r = call_bridge(
      "profile_set_counter",
      Py_BuildValue("(OK)", h->obj,
                    static_cast<unsigned long long>(value)));
  if (!r) return fail_py("set counter failed");
  Py_DECREF(r);
  return 0;
}

int MXProfileAdjustCounter(ProfileHandle counter_handle, int64_t value) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(counter_handle);
  PyObject* r = call_bridge(
      "profile_adjust_counter",
      Py_BuildValue("(OL)", h->obj, static_cast<long long>(value)));
  if (!r) return fail_py("adjust counter failed");
  Py_DECREF(r);
  return 0;
}

int MXProfileSetMarker(ProfileHandle domain, const char* instant_marker_name,
                       const char* scope) {
  ensure_python();
  Gil gil;
  auto* d = static_cast<PyHandle*>(domain);
  PyObject* r = call_bridge(
      "profile_set_marker",
      Py_BuildValue("(Oss)", d->obj, instant_marker_name, scope));
  if (!r) return fail_py("set marker failed");
  Py_DECREF(r);
  return 0;
}

// ---- quantization ABI ------------------------------------------------

int MXQuantizeSymbol(SymbolHandle sym_handle, SymbolHandle* ret_sym_handle,
                     mx_uint num_excluded_symbols,
                     const char** excluded_symbols, mx_uint num_offline,
                     const char** offline_params,
                     const char* quantized_dtype) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym_handle);
  PyObject* args = PyTuple_New(4);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(num_excluded_symbols,
                                     excluded_symbols));
  PyTuple_SET_ITEM(args, 2, str_list(num_offline, offline_params));
  PyTuple_SET_ITEM(args, 3, PyUnicode_FromString(
      quantized_dtype ? quantized_dtype : "int8"));
  PyObject* r = call_bridge("quantize_symbol", args);
  if (!r) return fail_py("quantize symbol failed");
  *ret_sym_handle = wrap_py(r);
  return 0;
}

int MXSetCalibTableToQuantizedSymbol(SymbolHandle qsym_handle,
                                     mx_uint num_layers,
                                     const char** layer_names,
                                     const float* min_ranges,
                                     const float* max_ranges,
                                     SymbolHandle* ret_sym_handle) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(qsym_handle);
  PyObject* mins = PyList_New(num_layers);
  PyObject* maxs = PyList_New(num_layers);
  for (mx_uint i = 0; i < num_layers; ++i) {
    PyList_SET_ITEM(mins, i, PyFloat_FromDouble(min_ranges[i]));
    PyList_SET_ITEM(maxs, i, PyFloat_FromDouble(max_ranges[i]));
  }
  PyObject* args = PyTuple_New(4);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, str_list(num_layers, layer_names));
  PyTuple_SET_ITEM(args, 2, mins);
  PyTuple_SET_ITEM(args, 3, maxs);
  PyObject* r = call_bridge("set_calib_table", args);
  if (!r) return fail_py("set calib table failed");
  *ret_sym_handle = wrap_py(r);
  return 0;
}

int MXGenBackendSubgraph(SymbolHandle sym_handle, const char* backend,
                         SymbolHandle* ret_sym_handle) {
  (void)backend;  // XLA fuses whole graphs internally: identity pass
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym_handle);
  Py_INCREF(h->obj);
  *ret_sym_handle = wrap_py(h->obj);
  return 0;
}

// ---- legacy Function registry ----------------------------------------

namespace {

// FunctionHandle = 1 + index into the sorted op-name cache (the same
// creator cache MXSymbolListAtomicSymbolCreators fills)
int ensure_creator_cache() {
  ExtTLS* e = ext_tls();
  if (!e->creator_names.empty()) return 0;
  mx_uint n = 0;
  AtomicSymbolCreator* unused = nullptr;
  return MXSymbolListAtomicSymbolCreators(&n, &unused);
}

}  // namespace

int MXListFunctions(mx_uint* out_size, FunctionHandle** out_array) {
  ensure_python();
  Gil gil;
  if (ensure_creator_cache() != 0) return -1;
  ExtTLS* e = ext_tls();
  *out_size = static_cast<mx_uint>(e->creators.size());
  *out_array = e->creators.data();
  return 0;
}

int MXGetFunction(const char* name, FunctionHandle* out) {
  ensure_python();
  Gil gil;
  if (ensure_creator_cache() != 0) return -1;
  ExtTLS* e = ext_tls();
  for (size_t i = 0; i < e->creator_names.size(); ++i) {
    if (e->creator_names[i] == name) {
      *out = e->creators[i];
      return 0;
    }
  }
  return fail(std::string("no function named ") + name);
}

int MXFuncGetInfo(FunctionHandle fun, const char** name,
                  const char** description, mx_uint* num_args,
                  const char*** arg_names, const char*** arg_type_infos,
                  const char*** arg_descriptions,
                  const char** return_type) {
  const char* key_var = nullptr;
  return MXSymbolGetAtomicSymbolInfo(fun, name, description, num_args,
                                     arg_names, arg_type_infos,
                                     arg_descriptions, &key_var,
                                     return_type);
}

int MXFuncDescribe(FunctionHandle fun, mx_uint* num_use_vars,
                   mx_uint* num_scalars, mx_uint* num_mutate_vars,
                   int* type_mask) {
  ensure_python();
  Gil gil;
  if (ensure_creator_cache() != 0) return -1;
  ExtTLS* e = ext_tls();
  size_t idx = reinterpret_cast<size_t>(fun);
  if (idx == 0 || idx > e->creator_names.size())
    return fail("invalid function handle");
  PyObject* r = call_bridge(
      "func_describe",
      Py_BuildValue("(s)", e->creator_names[idx - 1].c_str()));
  if (!r) return fail_py("func describe failed");
  *num_use_vars = static_cast<mx_uint>(
      PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, 0)));
  *num_scalars = static_cast<mx_uint>(
      PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, 1)));
  *num_mutate_vars = static_cast<mx_uint>(
      PyLong_AsUnsignedLong(PyTuple_GET_ITEM(r, 2)));
  *type_mask = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 3)));
  Py_DECREF(r);
  return 0;
}

int MXFuncInvoke(FunctionHandle fun, NDArrayHandle* use_vars,
                 float* scalar_args, NDArrayHandle* mutate_vars) {
  return MXFuncInvokeEx(fun, use_vars, scalar_args, mutate_vars, 0,
                        nullptr, nullptr);
}

int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle* use_vars,
                   float* scalar_args, NDArrayHandle* mutate_vars,
                   int num_params, char** param_keys, char** param_vals) {
  (void)scalar_args;  // described as 0 scalars: all params are keyworded
  ensure_python();
  Gil gil;
  if (ensure_creator_cache() != 0) return -1;
  ExtTLS* e = ext_tls();
  size_t idx = reinterpret_cast<size_t>(fun);
  if (idx == 0 || idx > e->creator_names.size())
    return fail("invalid function handle");
  const std::string& op = e->creator_names[idx - 1];
  // describe to learn the input arity
  mx_uint nin = 0, nsc = 0, nmut = 0;
  int mask = 0;
  if (MXFuncDescribe(fun, &nin, &nsc, &nmut, &mask) != 0) return -1;
  NDArrayHandle* out_ptr = nullptr;
  int num_out_int = static_cast<int>(nmut ? nmut : 1);
  // route through the modern invoke (keyworded params); write results
  // into mutate_vars
  int rc = MXImperativeInvoke(
      const_cast<char*>(op.c_str()), static_cast<int>(nin), use_vars,
      &num_out_int, &out_ptr, num_params,
      const_cast<const char**>(param_keys),
      const_cast<const char**>(param_vals));
  if (rc != 0) return rc;
  // every invoked output handle is freed exactly once, copy or not
  int copy_rc = 0;
  for (int i = 0; i < num_out_int; ++i) {
    if (copy_rc == 0 && mutate_vars && mutate_vars[i])
      copy_rc = MXNDArraySyncCopyFromNDArray(mutate_vars[i], out_ptr[i], -1);
    MXNDArrayFree(out_ptr[i]);
  }
  return copy_rc;
}

// ---- runtime misc completion -----------------------------------------

int MXLibInfoFeatures(const LibFeature** lib_features, size_t* size) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("lib_features", PyTuple_New(0));
  if (!r) return fail_py("lib features failed");
  static thread_local std::vector<std::string> name_store;
  static thread_local std::vector<LibFeature> feat_store;
  name_store.clear();
  feat_store.clear();
  Py_ssize_t n = PyList_Size(r);
  name_store.reserve(n);  // no reallocation: LibFeature keeps pointers
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* pair = PyList_GET_ITEM(r, i);
    name_store.push_back(safe_utf8(PyTuple_GET_ITEM(pair, 0)));
    unsigned char enabled = static_cast<unsigned char>(
        PyLong_AsLong(PyTuple_GET_ITEM(pair, 1)));
    feat_store.push_back(LibFeature{name_store.back().c_str(), enabled});
  }
  Py_DECREF(r);
  *lib_features = feat_store.data();
  *size = feat_store.size();
  return 0;
}

int MXSetNumOMPThreads(int thread_num) {
  (void)thread_num;  // XLA manages host threading
  return 0;
}

int MXEngineSetBulkSize(int bulk_size, int* prev_bulk_size) {
  (void)bulk_size;  // XLA's dispatch queue has no bulk-size knob
  if (prev_bulk_size) *prev_bulk_size = 0;
  return 0;
}

int MXGetGPUMemoryInformation(int dev, int* free_mem, int* total_mem) {
  (void)dev;
  if (free_mem) *free_mem = 0;
  if (total_mem) *total_mem = 0;  // no CUDA devices in the TPU runtime
  return 0;
}

int MXGetGPUMemoryInformation64(int dev, uint64_t* free_mem,
                                uint64_t* total_mem) {
  (void)dev;
  if (free_mem) *free_mem = 0;
  if (total_mem) *total_mem = 0;
  return 0;
}

namespace {

int rtc_unavailable() {
  return fail(
      "CUDA RTC has no TPU analogue; write user kernels in Pallas "
      "(mxnet_tpu.rtc.PallasModule)");
}

}  // namespace

int MXRtcCreate(char* name, mx_uint num_input, mx_uint num_output,
                char** input_names, char** output_names,
                NDArrayHandle* inputs, NDArrayHandle* outputs, char* kernel,
                void** out) {
  (void)name; (void)num_input; (void)num_output; (void)input_names;
  (void)output_names; (void)inputs; (void)outputs; (void)kernel; (void)out;
  return rtc_unavailable();
}

int MXRtcPush(void* handle, mx_uint num_input, mx_uint num_output,
              NDArrayHandle* inputs, NDArrayHandle* outputs,
              mx_uint gridDimX, mx_uint gridDimY, mx_uint gridDimZ,
              mx_uint blockDimX, mx_uint blockDimY, mx_uint blockDimZ) {
  (void)handle; (void)num_input; (void)num_output; (void)inputs;
  (void)outputs; (void)gridDimX; (void)gridDimY; (void)gridDimZ;
  (void)blockDimX; (void)blockDimY; (void)blockDimZ;
  return rtc_unavailable();
}

int MXRtcFree(void* handle) {
  (void)handle;
  return rtc_unavailable();
}

int MXRtcCudaModuleCreate(const char* source, int num_options,
                          const char** options, int num_exports,
                          const char** exports, void** out) {
  (void)source; (void)num_options; (void)options; (void)num_exports;
  (void)exports; (void)out;
  return rtc_unavailable();
}

int MXRtcCudaModuleFree(void* handle) {
  (void)handle;
  return rtc_unavailable();
}

int MXRtcCudaKernelCreate(void* handle, const char* name, int num_args,
                          int* is_ndarray, int* is_const, int* arg_types,
                          void** out) {
  (void)handle; (void)name; (void)num_args; (void)is_ndarray;
  (void)is_const; (void)arg_types; (void)out;
  return rtc_unavailable();
}

int MXRtcCudaKernelFree(void* handle) {
  (void)handle;
  return rtc_unavailable();
}

int MXRtcCudaKernelCall(void* handle, int dev_id, void** args,
                        mx_uint grid_dim_x, mx_uint grid_dim_y,
                        mx_uint grid_dim_z, mx_uint block_dim_x,
                        mx_uint block_dim_y, mx_uint block_dim_z,
                        mx_uint shared_mem) {
  (void)handle; (void)dev_id; (void)args; (void)grid_dim_x;
  (void)grid_dim_y; (void)grid_dim_z; (void)block_dim_x;
  (void)block_dim_y; (void)block_dim_z; (void)shared_mem;
  return rtc_unavailable();
}

}  // extern "C"
