// Imperative C ABI over an embedded CPython running mxnet_tpu
// (reference: src/c_api/c_api.cc + c_api_ndarray.cc:118-235 — there the
// ABI fronts the C++ engine/Imperative; here every invoke reaches the
// TPU op registry, whose ops are cached-jitted XLA computations, through
// mxnet_tpu.c_api_bridge).  Thread-safe via the GIL; errors land in the
// thread-local MXGetLastError string, matching the reference's
// MXAPIThreadLocalEntry error convention (src/c_api/c_api_error.cc).
#include "c_api.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

struct NDArrayObj {
  PyObject* array = nullptr;        // mxnet_tpu.ndarray.NDArray
  std::vector<mx_uint> shape_buf;   // backing for MXNDArrayGetShape
};

// thread-local result buffers (reference MXAPIThreadLocalEntry pattern:
// returned pointers stay valid until the next call on the same thread)
struct TLS {
  std::vector<NDArrayHandle> invoke_out;
  // load and op-name results use separate backing stores so calling
  // MXListAllOpNames does not invalidate a prior MXNDArrayLoad's names
  // (each is documented valid until the next call of the SAME kind)
  std::vector<std::string> load_str_store;
  std::vector<const char*> load_cstr_out;
  std::vector<NDArrayHandle> load_out;
  std::vector<std::string> op_str_store;
  std::vector<const char*> op_cstr_out;
};
TLS* tls() {
  thread_local TLS t;
  return &t;
}

int fail(const std::string& msg) {
  g_error = msg;
  return -1;
}

// PyUnicode_AsUTF8 returns nullptr on non-UTF8 names; constructing a
// std::string from nullptr is UB.  safe_utf8 is for diagnostic text only
// (error messages); data paths returning names to the caller must use
// utf8_or_null and propagate an error instead of renaming silently.
const char* safe_utf8(PyObject* s) {
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (!c) {
    PyErr_Clear();
    return "<non-utf8>";
  }
  return c;
}

const char* utf8_or_null(PyObject* s) {
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (!c) PyErr_Clear();
  return c;
}

int fail_py(const char* what) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = what;
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      msg += ": ";
      msg += safe_utf8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return fail(msg);
}

void ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    PyEval_SaveThread();
  }
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// import mxnet_tpu.c_api_bridge and fetch `name` (new reference)
PyObject* bridge_fn(const char* name) {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.c_api_bridge");
  if (!mod) return nullptr;
  PyObject* fn = PyObject_GetAttrString(mod, name);
  Py_DECREF(mod);
  return fn;
}

NDArrayObj* wrap(PyObject* array) {
  auto* obj = new NDArrayObj();
  obj->array = array;  // steals the reference
  return obj;
}

}  // namespace

extern "C" {

const char* MXGetLastError(void) { return g_error.c_str(); }

int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int dtype, NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("create");
  if (!fn) return fail_py("c_api_bridge.create not found");
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* arr =
      PyObject_CallFunction(fn, "Oiii", shp, dev_type, dev_id, dtype);
  Py_DECREF(shp);
  Py_DECREF(fn);
  if (!arr) return fail_py("NDArray create failed");
  *out = wrap(arr);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* eb = bridge_fn("element_bytes");
  if (!eb) return fail_py("bridge missing");
  PyObject* nbytes = PyObject_CallFunction(eb, "O", obj->array);
  Py_DECREF(eb);
  if (!nbytes) return fail_py("element size failed");
  size_t itemsize = PyLong_AsSize_t(nbytes);
  Py_DECREF(nbytes);
  PyObject* fn = bridge_fn("copy_from_bytes");
  if (!fn) return fail_py("bridge missing");
  PyObject* buf = PyBytes_FromStringAndSize(static_cast<const char*>(data),
                                            size * itemsize);
  PyObject* r = PyObject_CallFunction(fn, "OO", obj->array, buf);
  Py_DECREF(buf);
  Py_DECREF(fn);
  if (!r) return fail_py("copy from cpu failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* fn = bridge_fn("to_bytes");
  if (!fn) return fail_py("bridge missing");
  PyObject* bytes = PyObject_CallFunction(fn, "O", obj->array);
  Py_DECREF(fn);
  if (!bytes) return fail_py("copy to cpu failed");
  size_t blen = static_cast<size_t>(PyBytes_Size(bytes));
  size_t nelem = 0;
  {
    PyObject* sz = PyObject_GetAttrString(obj->array, "size");
    nelem = sz ? PyLong_AsSize_t(sz) : 0;
    Py_XDECREF(sz);
  }
  if (size != nelem) {
    // mirror the FromCPU contract exactly: the caller must pass the
    // element count, not merely a large-enough buffer
    Py_DECREF(bytes);
    return fail("destination size must equal array element count");
  }
  std::memcpy(data, PyBytes_AsString(bytes), blen);
  Py_DECREF(bytes);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_ndim,
                      const mx_uint** out_pdata) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* shape = PyObject_GetAttrString(obj->array, "shape");
  if (!shape) return fail_py("shape failed");
  Py_ssize_t n = PyTuple_Size(shape);
  obj->shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    obj->shape_buf[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, i)));
  Py_DECREF(shape);
  *out_ndim = static_cast<mx_uint>(n);
  *out_pdata = obj->shape_buf.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* fn = bridge_fn("dtype_code");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, "O", obj->array);
  Py_DECREF(fn);
  if (!r) return fail_py("dtype failed");
  *out_dtype = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* fn = bridge_fn("context_of");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, "O", obj->array);
  Py_DECREF(fn);
  if (!r) return fail_py("context failed");
  *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 0)));
  *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 1)));
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitToRead(NDArrayHandle handle) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = PyObject_CallMethod(obj->array, "wait_to_read", nullptr);
  if (!r) return fail_py("wait_to_read failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitAll(void) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("wait_all");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, nullptr);
  Py_DECREF(fn);
  if (!r) return fail_py("wait_all failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  Py_XDECREF(obj->array);
  delete obj;
  return 0;
}

int MXNDArraySave(const char* fname, mx_uint num_args, NDArrayHandle* args,
                  const char** keys) {
  Gil gil;
  PyObject* arrs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* a = static_cast<NDArrayObj*>(args[i])->array;
    Py_INCREF(a);
    PyList_SET_ITEM(arrs, i, a);
  }
  PyObject* names;
  if (keys) {
    names = PyList_New(num_args);
    for (mx_uint i = 0; i < num_args; ++i)
      PyList_SET_ITEM(names, i, PyUnicode_FromString(keys[i]));
  } else {
    names = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* fn = bridge_fn("save");
  if (!fn) {
    Py_DECREF(arrs);
    Py_DECREF(names);
    return fail_py("bridge missing");
  }
  PyObject* r = PyObject_CallFunction(fn, "sOO", fname, arrs, names);
  Py_DECREF(fn);
  Py_DECREF(arrs);
  Py_DECREF(names);
  if (!r) return fail_py("save failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("load");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, "s", fname);
  Py_DECREF(fn);
  if (!r) return fail_py("load failed");
  PyObject* names = PyTuple_GET_ITEM(r, 0);
  PyObject* arrays = PyTuple_GET_ITEM(r, 1);
  TLS* t = tls();
  t->load_out.clear();
  t->load_str_store.clear();
  t->load_cstr_out.clear();
  Py_ssize_t n = PyList_Size(arrays);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(arrays, i);
    Py_INCREF(a);
    t->load_out.push_back(wrap(a));
  }
  Py_ssize_t nn = PyList_Size(names);
  for (Py_ssize_t i = 0; i < nn; ++i) {
    const char* name = utf8_or_null(PyList_GET_ITEM(names, i));
    if (!name) {
      Py_DECREF(r);
      return fail("non-UTF8 array name in file");
    }
    t->load_str_store.push_back(name);
  }
  for (auto& s : t->load_str_store) t->load_cstr_out.push_back(s.c_str());
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(t->load_out.size());
  *out_arr = t->load_out.data();
  *out_name_size = static_cast<mx_uint>(t->load_cstr_out.size());
  *out_names = t->load_cstr_out.data();
  return 0;
}

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("list_ops");
  if (!fn) return fail_py("bridge missing");
  PyObject* r = PyObject_CallFunction(fn, nullptr);
  Py_DECREF(fn);
  if (!r) return fail_py("list_ops failed");
  TLS* t = tls();
  t->op_str_store.clear();
  t->op_cstr_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* name = utf8_or_null(PyList_GET_ITEM(r, i));
    if (!name) {
      Py_DECREF(r);
      return fail("non-UTF8 op name");
    }
    t->op_str_store.push_back(name);
  }
  Py_DECREF(r);
  for (auto& s : t->op_str_store) t->op_cstr_out.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(t->op_cstr_out.size());
  *out_array = t->op_cstr_out.data();
  return 0;
}

int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  ensure_python();
  Gil gil;
  PyObject* fn = bridge_fn("invoke");
  if (!fn) return fail_py("bridge missing");
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* a = static_cast<NDArrayObj*>(inputs[i])->array;
    Py_INCREF(a);
    PyList_SET_ITEM(ins, i, a);
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SET_ITEM(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SET_ITEM(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* r =
      PyObject_CallFunction(fn, "sOOO", op_name, ins, keys, vals);
  Py_DECREF(fn);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!r) return fail_py("invoke failed");
  TLS* t = tls();
  t->invoke_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(r, i);
    Py_INCREF(a);
    t->invoke_out.push_back(wrap(a));
  }
  Py_DECREF(r);
  *num_outputs = static_cast<int>(t->invoke_out.size());
  *outputs = t->invoke_out.data();
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------
// Symbol / Executor / KVStore ABI (reference c_api_symbolic.cc,
// c_api_executor.cc, MXKVStore*).  Handles are PyObject* boxes; the
// graph/executor logic lives in mxnet_tpu and is reached through the
// same bridge module as the NDArray plane.
// ---------------------------------------------------------------------

namespace {

struct PyHandle {
  PyObject* obj = nullptr;
};

PyHandle* wrap_py(PyObject* obj) {
  auto* h = new PyHandle();
  h->obj = obj;  // steals the reference
  return h;
}

// TLS stores for the symbol/executor plane
struct SymTLS {
  std::vector<std::string> str_store;
  std::vector<const char*> cstr_out;
  std::string json_store;
  // MXSymbolInferShape backing: three groups of (ndim, flat rows, row
  // pointers)
  std::vector<mx_uint> ndims[3];
  std::vector<std::vector<mx_uint>> rows[3];
  std::vector<const mx_uint*> row_ptrs[3];
  std::vector<NDArrayHandle> exec_out;
};
SymTLS* sym_tls() {
  thread_local SymTLS t;
  return &t;
}

// call bridge fn with pre-built args tuple; returns new ref or null
PyObject* call_bridge(const char* name, PyObject* args) {
  PyObject* fn = bridge_fn(name);
  if (!fn) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* r = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  return r;
}

PyObject* str_list(mx_uint n, const char** strs) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyUnicode_FromString(strs ? strs[i] : ""));
  return lst;
}

// list of borrowed NDArray objects (NULL handles become None)
PyObject* nd_list(mx_uint n, NDArrayHandle* arr) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyObject* a = arr && arr[i]
        ? static_cast<NDArrayObj*>(arr[i])->array : Py_None;
    Py_INCREF(a);
    PyList_SET_ITEM(lst, i, a);
  }
  return lst;
}

int return_str_list(PyObject* r, mx_uint* out_size,
                    const char*** out_array) {
  SymTLS* t = sym_tls();
  t->str_store.clear();
  t->cstr_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = utf8_or_null(PyList_GET_ITEM(r, i));
    if (!s) {
      Py_DECREF(r);
      return fail("non-UTF8 name");
    }
    t->str_store.push_back(s);
  }
  Py_DECREF(r);
  for (auto& s : t->str_store) t->cstr_out.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(t->cstr_out.size());
  *out_array = t->cstr_out.data();
  return 0;
}

}  // namespace

extern "C" {

int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("symbol_create_variable",
                            Py_BuildValue("(s)", name));
  if (!r) return fail_py("create variable failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolCreateAtomicSymbol(const char* op_name, mx_uint num_param,
                               const char** keys, const char** vals,
                               SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(op_name));
  PyTuple_SET_ITEM(args, 1, str_list(num_param, keys));
  PyTuple_SET_ITEM(args, 2, str_list(num_param, vals));
  PyObject* r = call_bridge("symbol_create_atomic", args);
  if (!r) return fail_py("create atomic symbol failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* sym_args) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* arg_list = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* a = static_cast<PyHandle*>(sym_args[i])->obj;
    Py_INCREF(a);
    PyList_SET_ITEM(arg_list, i, a);
  }
  PyObject* args = PyTuple_New(4);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, name ? PyUnicode_FromString(name)
                                 : (Py_INCREF(Py_None), Py_None));
  PyTuple_SET_ITEM(args, 2, str_list(keys ? num_args : 0, keys));
  PyTuple_SET_ITEM(args, 3, arg_list);
  PyObject* r = call_bridge("symbol_compose", args);
  if (!r) return fail_py("compose failed");
  // reference semantics: compose updates the handle in place
  Py_DECREF(h->obj);
  h->obj = r;
  return 0;
}

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("symbol_from_json",
                            Py_BuildValue("(s)", json));
  if (!r) return fail_py("symbol from json failed");
  *out = wrap_py(r);
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* r = call_bridge("symbol_to_json",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("symbol to json failed");
  const char* s = utf8_or_null(r);
  if (!s) {
    Py_DECREF(r);
    return fail("non-UTF8 json");
  }
  sym_tls()->json_store = s;
  Py_DECREF(r);
  *out_json = sym_tls()->json_store.c_str();
  return 0;
}

#define MXTPU_SYM_LIST(fn_name, bridge_name)                            \
  int fn_name(SymbolHandle sym, mx_uint* out_size,                      \
              const char*** out_array) {                                \
    ensure_python();                                                    \
    Gil gil;                                                            \
    auto* h = static_cast<PyHandle*>(sym);                              \
    PyObject* r = call_bridge(bridge_name,                              \
                              Py_BuildValue("(O)", h->obj));            \
    if (!r) return fail_py(bridge_name " failed");                      \
    return return_str_list(r, out_size, out_array);                     \
  }

MXTPU_SYM_LIST(MXSymbolListArguments, "symbol_list_arguments")
MXTPU_SYM_LIST(MXSymbolListOutputs, "symbol_list_outputs")
MXTPU_SYM_LIST(MXSymbolListAuxiliaryStates, "symbol_list_aux")
#undef MXTPU_SYM_LIST

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* key_list = str_list(num_args, keys);
  PyObject* ndims = PyList_New(num_args);
  mx_uint total = num_args ? arg_ind_ptr[num_args] : 0;
  PyObject* flat = PyList_New(total);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SET_ITEM(ndims, i, PyLong_FromUnsignedLong(
        arg_ind_ptr[i + 1] - arg_ind_ptr[i]));
  for (mx_uint i = 0; i < total; ++i)
    PyList_SET_ITEM(flat, i, PyLong_FromUnsignedLong(arg_shape_data[i]));
  PyObject* args = PyTuple_New(4);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, key_list);
  PyTuple_SET_ITEM(args, 2, ndims);
  PyTuple_SET_ITEM(args, 3, flat);
  PyObject* r = call_bridge("symbol_infer_shape", args);
  if (!r) return fail_py("infer shape failed");
  // r = (arg_ndims, arg_flat, out_ndims, out_flat, aux_ndims, aux_flat)
  SymTLS* t = sym_tls();
  int all_known = 1;
  mx_uint* sizes[3] = {in_shape_size, out_shape_size, aux_shape_size};
  const mx_uint** ndim_outs[3] = {in_shape_ndim, out_shape_ndim,
                                  aux_shape_ndim};
  const mx_uint*** data_outs[3] = {in_shape_data, out_shape_data,
                                   aux_shape_data};
  for (int g = 0; g < 3; ++g) {
    PyObject* nd_l = PyTuple_GetItem(r, 2 * g);
    PyObject* fl_l = PyTuple_GetItem(r, 2 * g + 1);
    t->ndims[g].clear();
    t->rows[g].clear();
    t->row_ptrs[g].clear();
    Py_ssize_t n = PyList_Size(nd_l);
    Py_ssize_t pos = 0;
    for (Py_ssize_t i = 0; i < n; ++i) {
      mx_uint nd_i = static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyList_GET_ITEM(nd_l, i)));
      t->ndims[g].push_back(nd_i);
      std::vector<mx_uint> row;
      for (mx_uint j = 0; j < nd_i; ++j, ++pos)
        row.push_back(static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyList_GET_ITEM(fl_l, pos))));
      if (nd_i == 0) all_known = 0;
      t->rows[g].push_back(std::move(row));
    }
    for (auto& row : t->rows[g]) t->row_ptrs[g].push_back(row.data());
    *sizes[g] = static_cast<mx_uint>(t->ndims[g].size());
    *ndim_outs[g] = t->ndims[g].data();
    *data_outs[g] = t->row_ptrs[g].data();
  }
  Py_DECREF(r);
  if (complete) *complete = all_known;
  return 0;
}

int MXSymbolFree(SymbolHandle sym) {
  if (!sym) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                   mx_uint num_args, NDArrayHandle* in_args,
                   NDArrayHandle* arg_grad_store,
                   const mx_uint* grad_req_type, mx_uint aux_states_len,
                   NDArrayHandle* aux_states, ExecutorHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(sym);
  PyObject* reqs = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SET_ITEM(reqs, i,
                    PyLong_FromUnsignedLong(grad_req_type[i]));
  PyObject* args = PyTuple_New(7);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(dev_type));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(dev_id));
  PyTuple_SET_ITEM(args, 3, nd_list(num_args, in_args));
  PyTuple_SET_ITEM(args, 4, nd_list(num_args, arg_grad_store));
  PyTuple_SET_ITEM(args, 5, reqs);
  PyTuple_SET_ITEM(args, 6, nd_list(aux_states_len, aux_states));
  PyObject* r = call_bridge("executor_bind", args);
  if (!r) return fail_py("executor bind failed");
  *out = wrap_py(r);
  return 0;
}

int MXExecutorForward(ExecutorHandle ex, int is_train) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* r = call_bridge("executor_forward",
                            Py_BuildValue("(Oi)", h->obj, is_train));
  if (!r) return fail_py("executor forward failed");
  Py_DECREF(r);  // outputs re-fetched via MXExecutorOutputs
  return 0;
}

int MXExecutorBackward(ExecutorHandle ex, mx_uint len,
                       NDArrayHandle* head_grads) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* args = PyTuple_New(2);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, nd_list(len, head_grads));
  PyObject* r = call_bridge("executor_backward", args);
  if (!r) return fail_py("executor backward failed");
  Py_DECREF(r);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle ex, mx_uint* out_size,
                      NDArrayHandle** out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  PyObject* r = call_bridge("executor_outputs",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("executor outputs failed");
  SymTLS* t = sym_tls();
  t->exec_out.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* a = PyList_GET_ITEM(r, i);
    Py_INCREF(a);
    t->exec_out.push_back(wrap(a));
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(t->exec_out.size());
  *out = t->exec_out.data();
  return 0;
}

int MXExecutorFree(ExecutorHandle ex) {
  if (!ex) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(ex);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("kv_create", Py_BuildValue("(s)", type));
  if (!r) return fail_py("kvstore create failed");
  *out = wrap_py(r);
  return 0;
}

namespace {
int kv_keyed_call(const char* bridge_name, KVStoreHandle kv, mx_uint num,
                  const int* keys, NDArrayHandle* vals, int priority,
                  bool with_priority) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* key_list = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SET_ITEM(key_list, i, PyLong_FromLong(keys[i]));
  PyObject* args = PyTuple_New(with_priority ? 4 : 3);
  Py_INCREF(h->obj);
  PyTuple_SET_ITEM(args, 0, h->obj);
  PyTuple_SET_ITEM(args, 1, key_list);
  PyTuple_SET_ITEM(args, 2, nd_list(num, vals));
  if (with_priority)
    PyTuple_SET_ITEM(args, 3, PyLong_FromLong(priority));
  PyObject* r = call_bridge(bridge_name, args);
  if (!r) return fail_py("kvstore call failed");
  Py_DECREF(r);
  return 0;
}
}  // namespace

int MXKVStoreInit(KVStoreHandle kv, mx_uint num, const int* keys,
                  NDArrayHandle* vals) {
  return kv_keyed_call("kv_init", kv, num, keys, vals, 0, false);
}

int MXKVStorePush(KVStoreHandle kv, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  return kv_keyed_call("kv_push", kv, num, keys, vals, priority, true);
}

int MXKVStorePull(KVStoreHandle kv, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  return kv_keyed_call("kv_pull", kv, num, keys, vals, priority, true);
}

int MXKVStoreGetRank(KVStoreHandle kv, int* rank) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_rank", Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("kv rank failed");
  *rank = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetGroupSize(KVStoreHandle kv, int* size) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  PyObject* r = call_bridge("kv_num_workers",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("kv num_workers failed");
  *size = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreFree(KVStoreHandle kv) {
  if (!kv) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(kv);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXAutogradSetIsRecording(int is_recording, int* prev) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("autograd_set_recording",
                            Py_BuildValue("(i)", is_recording));
  if (!r) return fail_py("set recording failed");
  if (prev) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradSetIsTraining(int is_training, int* prev) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("autograd_set_training",
                            Py_BuildValue("(i)", is_training));
  if (!r) return fail_py("set training failed");
  if (prev) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            NDArrayHandle* grad_handles) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, nd_list(num_var, var_handles));
  PyTuple_SET_ITEM(args, 1, nd_list(num_var, grad_handles));
  PyObject* r = call_bridge("autograd_mark_variables", args);
  if (!r) return fail_py("mark variables failed");
  Py_DECREF(r);
  return 0;
}

int MXAutogradBackward(mx_uint num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(4);
  PyTuple_SET_ITEM(args, 0, nd_list(num_output, output_handles));
  PyTuple_SET_ITEM(args, 1,
                   nd_list(ograd_handles ? num_output : 0,
                           ograd_handles));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(retain_graph));
  PyTuple_SET_ITEM(args, 3, PyLong_FromLong(1));
  PyObject* r = call_bridge("autograd_backward", args);
  if (!r) return fail_py("backward failed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  auto* obj = static_cast<NDArrayObj*>(handle);
  PyObject* r = call_bridge("ndarray_get_grad",
                            Py_BuildValue("(O)", obj->array));
  if (!r) return fail_py("get grad failed");
  *out = wrap(r);
  return 0;
}

int MXListDataIters(mx_uint* out_size, const char*** out_array) {
  ensure_python();
  Gil gil;
  PyObject* r = call_bridge("dataiter_list", PyTuple_New(0));
  if (!r) return fail_py("list data iters failed");
  return return_str_list(r, out_size, out_array);
}

int MXDataIterCreateIter(const char* name, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out) {
  ensure_python();
  Gil gil;
  PyObject* args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, PyUnicode_FromString(name));
  PyTuple_SET_ITEM(args, 1, str_list(num_param, keys));
  PyTuple_SET_ITEM(args, 2, str_list(num_param, vals));
  PyObject* r = call_bridge("dataiter_create", args);
  if (!r) return fail_py("create data iter failed");
  *out = wrap_py(r);
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("dataiter_next",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter next failed");
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("dataiter_before_first",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter reset failed");
  Py_DECREF(r);
  return 0;
}

namespace {
int iter_get_array(const char* fn, DataIterHandle handle,
                   NDArrayHandle* out) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge(fn, Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter get failed");
  *out = wrap(r);
  return 0;
}
}  // namespace

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out) {
  return iter_get_array("dataiter_get_data", handle, out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out) {
  return iter_get_array("dataiter_get_label", handle, out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int* pad) {
  ensure_python();
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  PyObject* r = call_bridge("dataiter_get_pad",
                            Py_BuildValue("(O)", h->obj));
  if (!r) return fail_py("iter pad failed");
  *pad = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXDataIterFree(DataIterHandle handle) {
  if (!handle) return 0;
  Gil gil;
  auto* h = static_cast<PyHandle*>(handle);
  Py_XDECREF(h->obj);
  delete h;
  return 0;
}

int MXNotifyShutdown(void) { return 0; }

}  // extern "C"
