// Round-4 ABI client: exercises the planes a full language frontend
// needs beyond basic NDArray/symbol/executor calls — CachedOp inference
// (reference cpp-package inference idiom), an updater-driven KVStore
// training step (reference kvstore custom-updater idiom), DLPack
// interop, RecordIO, raw-byte serde, executor monitor callbacks, symbol
// attributes/type inference/introspection, profiler control, and the
// autograd extras.  Prints ABI_EXTRAS_OK when every check passes.
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "c_api.h"

#define CHECK_OK(call)                                             \
  do {                                                             \
    if ((call) != 0) {                                             \
      std::fprintf(stderr, "FAILED %s: %s\n", #call,               \
                   MXGetLastError());                              \
      return 1;                                                    \
    }                                                              \
  } while (0)

#define EXPECT(cond)                                               \
  do {                                                             \
    if (!(cond)) {                                                 \
      std::fprintf(stderr, "EXPECT failed: %s\n", #cond);          \
      return 1;                                                    \
    }                                                              \
  } while (0)

namespace {

NDArrayHandle make_filled(const std::vector<mx_uint>& shape, float v) {
  NDArrayHandle h = nullptr;
  if (MXNDArrayCreate(shape.data(), (mx_uint)shape.size(), 1, 0, 0, &h))
    return nullptr;
  size_t n = 1;
  for (mx_uint s : shape) n *= s;
  std::vector<float> buf(n, v);
  if (MXNDArraySyncCopyFromCPU(h, buf.data(), n)) return nullptr;
  return h;
}

int read_floats(NDArrayHandle h, std::vector<float>* out) {
  mx_uint ndim = 0;
  const mx_uint* dims = nullptr;
  if (MXNDArrayGetShape(h, &ndim, &dims)) return -1;
  size_t n = 1;
  for (mx_uint i = 0; i < ndim; ++i) n *= dims[i];
  out->resize(n);
  return MXNDArraySyncCopyToCPU(h, out->data(), n);
}

// KVStore updater: local -= 0.5 * recv, through ABI invokes only
int g_updater_calls = 0;
void sgd_updater(int key, NDArrayHandle recv, NDArrayHandle local,
                 void* handle) {
  (void)key;
  (void)handle;
  ++g_updater_calls;
  NDArrayHandle ins[2] = {local, recv};
  int nout = 1;
  NDArrayHandle out_arr[1] = {local};
  NDArrayHandle* outs = out_arr;
  const char* keys[] = {"lr", "wd"};
  const char* vals[] = {"0.5", "0.0"};
  if (MXImperativeInvoke("sgd_update", 2, ins, &nout, &outs, 2, keys,
                         vals) != 0)
    std::fprintf(stderr, "updater invoke failed: %s\n", MXGetLastError());
}

int g_monitor_calls = 0;
void monitor_cb(const char* name, NDArrayHandle arr, void* handle) {
  (void)name;
  (void)arr;
  (void)handle;
  ++g_monitor_calls;
}

}  // namespace

int main() {
  // ---- NDArray extras ------------------------------------------------
  NDArrayHandle a = make_filled({4, 3}, 2.0f);
  EXPECT(a != nullptr);
  int stype = -1;
  CHECK_OK(MXNDArrayGetStorageType(a, &stype));
  EXPECT(stype == 1);

  NDArrayHandle sl = nullptr;
  CHECK_OK(MXNDArraySlice(a, 1, 3, &sl));
  mx_uint ndim = 0;
  const mx_uint* dims = nullptr;
  CHECK_OK(MXNDArrayGetShape(sl, &ndim, &dims));
  EXPECT(ndim == 2 && dims[0] == 2 && dims[1] == 3);

  NDArrayHandle row = nullptr;
  CHECK_OK(MXNDArrayAt(a, 0, &row));
  CHECK_OK(MXNDArrayGetShape(row, &ndim, &dims));
  EXPECT(ndim == 1 && dims[0] == 3);

  int rdims[2] = {3, 4};
  NDArrayHandle rs = nullptr;
  CHECK_OK(MXNDArrayReshape(a, 2, rdims, &rs));
  CHECK_OK(MXNDArrayGetShape(rs, &ndim, &dims));
  EXPECT(dims[0] == 3 && dims[1] == 4);

  // raw-byte serde round trip
  size_t raw_size = 0;
  const char* raw = nullptr;
  CHECK_OK(MXNDArraySaveRawBytes(a, &raw_size, &raw));
  EXPECT(raw_size > 0);
  NDArrayHandle a2 = nullptr;
  CHECK_OK(MXNDArrayLoadFromRawBytes(raw, raw_size, &a2));
  std::vector<float> va, va2;
  EXPECT(read_floats(a, &va) == 0 && read_floats(a2, &va2) == 0);
  EXPECT(va == va2);

  // DLPack round trip
  DLManagedTensorHandle dl = nullptr;
  CHECK_OK(MXNDArrayToDLPack(a, &dl));
  NDArrayHandle a3 = nullptr;
  CHECK_OK(MXNDArrayFromDLPack(dl, &a3));
  std::vector<float> va3;
  EXPECT(read_floats(a3, &va3) == 0);
  EXPECT(va3 == va);

  // InvokeEx surfaces storage types
  {
    NDArrayHandle ins[1] = {a};
    int nout = 0;
    NDArrayHandle* outs = nullptr;
    const int* stypes = nullptr;
    CHECK_OK(MXImperativeInvokeEx("relu", 1, ins, &nout, &outs, 0,
                                  nullptr, nullptr, &stypes));
    EXPECT(nout == 1 && stypes[0] == 1);
    CHECK_OK(MXNDArrayFree(outs[0]));
  }

  // ---- CachedOp inference (reference cpp-package idiom) --------------
  SymbolHandle x = nullptr;
  CHECK_OK(MXSymbolCreateVariable("x", &x));
  SymbolHandle relu_op = nullptr;
  CHECK_OK(MXSymbolCreateAtomicSymbol("relu", 0, nullptr, nullptr,
                                      &relu_op));
  SymbolHandle args1[1] = {x};
  CHECK_OK(MXSymbolCompose(relu_op, "act", 1, nullptr, args1));

  CachedOpHandle cop = nullptr;
  CHECK_OK(MXCreateCachedOp(relu_op, &cop));
  {
    NDArrayHandle neg = make_filled({2, 2}, -1.5f);
    EXPECT(neg != nullptr);
    for (int rep = 0; rep < 2; ++rep) {  // second call = cache hit
      NDArrayHandle ins[1] = {neg};
      int nout = 0;
      NDArrayHandle* outs = nullptr;
      const int* stypes = nullptr;
      CHECK_OK(MXInvokeCachedOpEx(cop, 1, ins, &nout, &outs, &stypes));
      EXPECT(nout == 1 && stypes[0] == 1);
      std::vector<float> vo;
      EXPECT(read_floats(outs[0], &vo) == 0);
      for (float f : vo) EXPECT(f == 0.0f);
      CHECK_OK(MXNDArrayFree(outs[0]));
    }
    CHECK_OK(MXNDArrayFree(neg));
  }
  CHECK_OK(MXFreeCachedOp(cop));

  // ---- updater-driven KVStore (reference custom-updater idiom) ------
  KVStoreHandle kv = nullptr;
  CHECK_OK(MXKVStoreCreate("local", &kv));
  const char* kv_type = nullptr;
  CHECK_OK(MXKVStoreGetType(kv, &kv_type));
  EXPECT(std::string(kv_type) == "local");
  CHECK_OK(MXKVStoreSetUpdater(kv, sgd_updater, nullptr));

  {
    int key = 9;
    NDArrayHandle w0 = make_filled({4}, 1.0f);
    CHECK_OK(MXKVStoreInit(kv, 1, &key, &w0));
    NDArrayHandle g = make_filled({4}, 1.0f);
    CHECK_OK(MXKVStorePush(kv, 1, &key, &g, 0));
    NDArrayHandle got = make_filled({4}, 0.0f);
    CHECK_OK(MXKVStorePull(kv, 1, &key, &got, 0));
    std::vector<float> vw;
    EXPECT(read_floats(got, &vw) == 0);
    for (float f : vw) EXPECT(std::fabs(f - 0.5f) < 1e-6f);  // 1 - 0.5*1
    EXPECT(g_updater_calls == 1);
    CHECK_OK(MXNDArrayFree(w0));
    CHECK_OK(MXNDArrayFree(g));
    CHECK_OK(MXNDArrayFree(got));
  }

  // string keys
  {
    const char* skey = "emb_weight";
    NDArrayHandle w0 = make_filled({3}, 2.0f);
    CHECK_OK(MXKVStoreInitEx(kv, 1, &skey, &w0));
    NDArrayHandle got = make_filled({3}, 0.0f);
    CHECK_OK(MXKVStorePullEx(kv, 1, &skey, &got, 0));
    std::vector<float> vw;
    EXPECT(read_floats(got, &vw) == 0);
    for (float f : vw) EXPECT(f == 2.0f);
    CHECK_OK(MXNDArrayFree(w0));
    CHECK_OK(MXNDArrayFree(got));
  }

  CHECK_OK(MXKVStoreBarrier(kv));
  int is_worker = -1;
  CHECK_OK(MXKVStoreIsWorkerNode(&is_worker));
  EXPECT(is_worker == 1);

  // row-sparse pull
  {
    int key = 21;
    NDArrayHandle table = nullptr;
    mx_uint tshape[2] = {6, 2};
    CHECK_OK(MXNDArrayCreate(tshape, 2, 1, 0, 0, &table));
    std::vector<float> tv(12);
    for (int i = 0; i < 12; ++i) tv[i] = (float)i;
    CHECK_OK(MXNDArraySyncCopyFromCPU(table, tv.data(), 12));
    CHECK_OK(MXKVStoreInit(kv, 1, &key, &table));
    NDArrayHandle dst = make_filled({6, 2}, 0.0f);
    NDArrayHandle rows = nullptr;
    mx_uint rshape[1] = {2};
    // int32 row ids: int64 (code 6) needs MXNET_INT64_TENSOR_SIZE=1,
    // and MXNDArrayCreate fails loudly rather than truncating silently
    CHECK_OK(MXNDArrayCreate(rshape, 1, 1, 0, 4 /*int32*/, &rows));
    int32_t ridx[2] = {1, 4};
    CHECK_OK(MXNDArraySyncCopyFromCPU(rows, ridx, 2));
    NDArrayHandle rlist[1] = {rows};
    CHECK_OK(MXKVStorePullRowSparse(kv, 1, &key, &dst, rlist, 0));
    std::vector<float> vd;
    EXPECT(read_floats(dst, &vd) == 0);
    EXPECT(vd[2] == 2.0f && vd[3] == 3.0f);   // row 1
    EXPECT(vd[8] == 8.0f && vd[9] == 9.0f);   // row 4
    EXPECT(vd[0] == 0.0f);                    // untouched row zeroed
    CHECK_OK(MXNDArrayFree(table));
    CHECK_OK(MXNDArrayFree(dst));
    CHECK_OK(MXNDArrayFree(rows));
  }
  CHECK_OK(MXKVStoreFree(kv));

  // ---- RecordIO ------------------------------------------------------
  {
    const char* path = "abi_extras_test.rec";
    RecordIOHandle w = nullptr;
    CHECK_OK(MXRecordIOWriterCreate(path, &w));
    CHECK_OK(MXRecordIOWriterWriteRecord(w, "hello", 5));
    CHECK_OK(MXRecordIOWriterWriteRecord(w, "worlds", 6));
    size_t pos = 0;
    CHECK_OK(MXRecordIOWriterTell(w, &pos));
    EXPECT(pos > 0);
    CHECK_OK(MXRecordIOWriterFree(w));

    RecordIOHandle r = nullptr;
    CHECK_OK(MXRecordIOReaderCreate(path, &r));
    const char* buf = nullptr;
    size_t size = 0;
    CHECK_OK(MXRecordIOReaderReadRecord(r, &buf, &size));
    EXPECT(size == 5 && std::memcmp(buf, "hello", 5) == 0);
    CHECK_OK(MXRecordIOReaderReadRecord(r, &buf, &size));
    EXPECT(size == 6 && std::memcmp(buf, "worlds", 6) == 0);
    CHECK_OK(MXRecordIOReaderReadRecord(r, &buf, &size));
    EXPECT(buf == nullptr && size == 0);  // EOF
    CHECK_OK(MXRecordIOReaderFree(r));
    std::remove(path);
  }

  // ---- Symbol extras -------------------------------------------------
  {
    CHECK_OK(MXSymbolSetAttr(x, "__lr_mult__", "2.5"));
    const char* av = nullptr;
    int ok = 0;
    CHECK_OK(MXSymbolGetAttr(x, "__lr_mult__", &av, &ok));
    EXPECT(ok == 1 && std::string(av) == "2.5");

    mx_uint nout = 0;
    CHECK_OK(MXSymbolGetNumOutputs(relu_op, &nout));
    EXPECT(nout == 1);

    SymbolHandle cp = nullptr;
    CHECK_OK(MXSymbolCopy(relu_op, &cp));
    const char* j1 = nullptr;
    CHECK_OK(MXSymbolSaveToJSON(cp, &j1));
    std::string json1(j1);
    const char* j2 = nullptr;
    CHECK_OK(MXSymbolSaveToJSON(relu_op, &j2));
    EXPECT(json1 == std::string(j2));
    CHECK_OK(MXSymbolFree(cp));

    // type inference: fp32 in -> fp32 out
    const char* tkeys[1] = {"x"};
    int tcodes[1] = {0};
    mx_uint in_n = 0, out_n = 0, aux_n = 0;
    const int *in_t = nullptr, *out_t = nullptr, *aux_t = nullptr;
    int complete = 0;
    CHECK_OK(MXSymbolInferType(relu_op, 1, tkeys, tcodes, &in_n, &in_t,
                               &out_n, &out_t, &aux_n, &aux_t,
                               &complete));
    EXPECT(complete == 1 && out_n == 1 && out_t[0] == 0);

    // file round trip
    CHECK_OK(MXSymbolSaveToFile(relu_op, "abi_extras_sym.json"));
    SymbolHandle loaded = nullptr;
    CHECK_OK(MXSymbolCreateFromFile("abi_extras_sym.json", &loaded));
    mx_uint n2 = 0;
    CHECK_OK(MXSymbolGetNumOutputs(loaded, &n2));
    EXPECT(n2 == 1);
    CHECK_OK(MXSymbolFree(loaded));
    std::remove("abi_extras_sym.json");

    // op introspection (frontend-codegen surface)
    mx_uint n_ops = 0;
    AtomicSymbolCreator* creators = nullptr;
    CHECK_OK(MXSymbolListAtomicSymbolCreators(&n_ops, &creators));
    EXPECT(n_ops > 250);
    bool found_conv = false;
    for (mx_uint i = 0; i < n_ops; ++i) {
      const char* nm = nullptr;
      CHECK_OK(MXSymbolGetAtomicSymbolName(creators[i], &nm));
      if (std::string(nm) == "Convolution") {
        const char *name = nullptr, *desc = nullptr, *kv = nullptr,
                   *rt = nullptr;
        mx_uint nargs = 0;
        const char **anames = nullptr, **atypes = nullptr,
                   **adescs = nullptr;
        CHECK_OK(MXSymbolGetAtomicSymbolInfo(
            creators[i], &name, &desc, &nargs, &anames, &atypes, &adescs,
            &kv, &rt));
        EXPECT(nargs > 0);
        bool has_kernel = false;
        for (mx_uint k = 0; k < nargs; ++k)
          if (std::string(anames[k]) == "kernel") has_kernel = true;
        EXPECT(has_kernel);
        found_conv = true;
        break;
      }
    }
    EXPECT(found_conv);
  }

  // ---- Executor monitor callback ------------------------------------
  {
    // y = relu(w); bind and watch intermediates
    SymbolHandle w = nullptr;
    CHECK_OK(MXSymbolCreateVariable("w", &w));
    SymbolHandle net = nullptr;
    CHECK_OK(MXSymbolCreateAtomicSymbol("relu", 0, nullptr, nullptr,
                                        &net));
    SymbolHandle cargs[1] = {w};
    CHECK_OK(MXSymbolCompose(net, "mon", 1, nullptr, cargs));
    NDArrayHandle warr = make_filled({2, 2}, -1.0f);
    NDArrayHandle grads[1] = {nullptr};
    mx_uint reqs[1] = {0};
    ExecutorHandle ex = nullptr;
    CHECK_OK(MXExecutorBind(net, 1, 0, 1, &warr, grads, reqs, 0, nullptr,
                            &ex));
    CHECK_OK(MXExecutorSetMonitorCallback(ex, monitor_cb, nullptr));
    CHECK_OK(MXExecutorForward(ex, 0));
    EXPECT(g_monitor_calls > 0);
    CHECK_OK(MXExecutorFree(ex));
    CHECK_OK(MXNDArrayFree(warr));
    CHECK_OK(MXSymbolFree(net));
    CHECK_OK(MXSymbolFree(w));
  }

  // ---- Profiler ------------------------------------------------------
  {
    const char* pkeys[1] = {"filename"};
    const char* pvals[1] = {"abi_extras_profile.json"};
    CHECK_OK(MXSetProfilerConfig(1, pkeys, pvals));
    CHECK_OK(MXSetProfilerState(1));
    NDArrayHandle t1 = make_filled({8}, 1.0f);
    NDArrayHandle ins[1] = {t1};
    int nout = 0;
    NDArrayHandle* outs = nullptr;
    CHECK_OK(MXImperativeInvoke("relu", 1, ins, &nout, &outs, 0, nullptr,
                                nullptr));
    CHECK_OK(MXNDArrayFree(outs[0]));
    CHECK_OK(MXNDArrayFree(t1));
    CHECK_OK(MXSetProfilerState(0));
    const char* stats = nullptr;
    CHECK_OK(MXAggregateProfileStatsPrint(&stats, 0));
    EXPECT(stats != nullptr);
    CHECK_OK(MXDumpProfile(1));
    std::remove("abi_extras_profile.json");
  }

  // ---- Autograd extras ----------------------------------------------
  {
    unsigned char rec = 9;
    CHECK_OK(MXAutogradIsRecording(&rec));
    EXPECT(rec == 0);
    NDArrayHandle v = make_filled({3}, 1.0f);
    NDArrayHandle vgrad = make_filled({3}, 0.0f);
    NDArrayHandle vars[1] = {v};
    NDArrayHandle gbufs[1] = {vgrad};
    CHECK_OK(MXAutogradMarkVariables(1, vars, gbufs));
    int prev = 0;
    CHECK_OK(MXAutogradSetIsRecording(1, &prev));
    NDArrayHandle ins[1] = {v};
    int nout = 0;
    NDArrayHandle* outs = nullptr;
    const char* keys[] = {"scalar"};
    const char* vals[] = {"3.0"};
    CHECK_OK(MXImperativeInvoke("_mul_scalar", 1, ins, &nout, &outs, 1,
                                keys, vals));
    NDArrayHandle y = outs[0];
    CHECK_OK(MXAutogradSetIsRecording(0, &prev));
    NDArrayHandle* grad_out = nullptr;
    const int* gstypes = nullptr;
    NDArrayHandle heads[1] = {y};
    CHECK_OK(MXAutogradBackwardEx(1, heads, nullptr, 1, vars, 0, 0, 1,
                                  &grad_out, &gstypes));
    std::vector<float> gv;
    EXPECT(read_floats(grad_out[0], &gv) == 0);
    for (float f : gv) EXPECT(std::fabs(f - 3.0f) < 1e-6f);
    EXPECT(gstypes[0] == 1);
    CHECK_OK(MXNDArrayFree(grad_out[0]));
    CHECK_OK(MXNDArrayFree(y));
    CHECK_OK(MXNDArrayFree(v));
    CHECK_OK(MXNDArrayFree(vgrad));
  }

  // ---- Runtime misc --------------------------------------------------
  int version = 0;
  CHECK_OK(MXGetVersion(&version));
  EXPECT(version >= 10000);
  CHECK_OK(MXRandomSeed(42));
  int ndev = -1;
  CHECK_OK(MXGetGPUCount(&ndev));
  EXPECT(ndev >= 0);

  CHECK_OK(MXNDArrayFree(a));
  CHECK_OK(MXNDArrayFree(a2));
  CHECK_OK(MXNDArrayFree(a3));
  CHECK_OK(MXNDArrayFree(sl));
  CHECK_OK(MXNDArrayFree(row));
  CHECK_OK(MXNDArrayFree(rs));
  CHECK_OK(MXSymbolFree(relu_op));
  CHECK_OK(MXSymbolFree(x));

  std::printf("ABI_EXTRAS_OK\n");
  return 0;
}
