// Round-4b ABI client: the completion planes — symbol extras (group,
// children, grad, partial inference, print), SimpleBind/Reshape/BindX
// executor flows, KVStore sparse/compression/server surface, NDArray
// data/copy/sparse-format extras, the profile object ABI, the
// quantization passes, the legacy Function registry, and runtime
// feature introspection.  Prints ABI_R4_OK when every check passes.
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "c_api.h"

#define CHECK_OK(call)                                             \
  do {                                                             \
    if ((call) != 0) {                                             \
      std::fprintf(stderr, "FAILED %s: %s\n", #call,               \
                   MXGetLastError());                              \
      return 1;                                                    \
    }                                                              \
  } while (0)

#define EXPECT(cond)                                               \
  do {                                                             \
    if (!(cond)) {                                                 \
      std::fprintf(stderr, "EXPECT failed: %s\n", #cond);          \
      return 1;                                                    \
    }                                                              \
  } while (0)

#define EXPECT_FAIL(call)                                          \
  do {                                                             \
    if ((call) == 0) {                                             \
      std::fprintf(stderr, "expected failure: %s\n", #call);       \
      return 1;                                                    \
    }                                                              \
  } while (0)

namespace {

NDArrayHandle make_filled(const std::vector<mx_uint>& shape, float v) {
  NDArrayHandle h = nullptr;
  if (MXNDArrayCreate(shape.data(), (mx_uint)shape.size(), 1, 0, 0, &h))
    return nullptr;
  size_t n = 1;
  for (mx_uint s : shape) n *= s;
  std::vector<float> buf(n, v);
  if (MXNDArraySyncCopyFromCPU(h, buf.data(), n)) return nullptr;
  return h;
}

// data -> FullyConnected(num_hidden) with auto-created weight/bias
SymbolHandle make_fc(const char* name, const char* hidden) {
  SymbolHandle data = nullptr;
  if (MXSymbolCreateVariable("data", &data)) {
    std::fprintf(stderr, "make_fc variable: %s\n", MXGetLastError());
    return nullptr;
  }
  const char* pk[] = {"num_hidden"};
  const char* pv[] = {hidden};
  SymbolHandle fc = nullptr;
  if (MXSymbolCreateAtomicSymbol("FullyConnected", 1, pk, pv, &fc)) {
    std::fprintf(stderr, "make_fc atomic: %s\n", MXGetLastError());
    return nullptr;
  }
  const char* ik[] = {"data"};
  SymbolHandle ins[] = {data};
  if (MXSymbolCompose(fc, name, 1, ik, ins)) {
    std::fprintf(stderr, "make_fc compose: %s\n", MXGetLastError());
    return nullptr;
  }
  return fc;
}

}  // namespace

int main() {
  // ---- symbol extras -------------------------------------------------
  SymbolHandle fc = make_fc("fc1", "4");
  EXPECT(fc != nullptr);

  const char* name = nullptr;
  int success = 0;
  CHECK_OK(MXSymbolGetName(fc, &name, &success));
  EXPECT(success == 1 && std::string(name) == "fc1");

  SymbolHandle grp = nullptr;
  SymbolHandle two[] = {fc, fc};
  CHECK_OK(MXSymbolCreateGroup(2, two, &grp));
  mx_uint n_out = 0;
  const char** out_names = nullptr;
  CHECK_OK(MXSymbolListOutputs(grp, &n_out, &out_names));
  EXPECT(n_out == 2);

  SymbolHandle children = nullptr;
  CHECK_OK(MXSymbolGetChildren(fc, &children));
  EXPECT(children != nullptr);
  CHECK_OK(MXSymbolListOutputs(children, &n_out, &out_names));
  EXPECT(n_out == 3);  // data, fc1_weight, fc1_bias

  SymbolHandle* input_syms = nullptr;
  int n_inputs = 0;
  CHECK_OK(MXSymbolGetInputSymbols(fc, &input_syms, &n_inputs));
  EXPECT(n_inputs == 3);

  // partial shape inference: only data known -> weight rows known
  const char* sk[] = {"data"};
  mx_uint ind_ptr[] = {0, 2};
  mx_uint sdata[] = {8, 5};
  mx_uint in_sz, out_sz, aux_sz;
  const mx_uint *in_nd, *out_nd, *aux_nd;
  const mx_uint **in_sh, **out_sh, **aux_sh;
  int complete = -1;
  CHECK_OK(MXSymbolInferShapePartial(fc, 1, sk, ind_ptr, sdata, &in_sz,
                                     &in_nd, &in_sh, &out_sz, &out_nd,
                                     &out_sh, &aux_sz, &aux_nd, &aux_sh,
                                     &complete));
  EXPECT(in_sz == 3 && out_sz == 1);
  EXPECT(out_sh[0][0] == 8 && out_sh[0][1] == 4);

  int tk[] = {0};  // data: float32
  mx_uint it_sz, ot_sz, at_sz;
  const int *it_d, *ot_d, *at_d;
  CHECK_OK(MXSymbolInferTypePartial(fc, 1, sk, tk, &it_sz, &it_d, &ot_sz,
                                    &ot_d, &at_sz, &at_d, &complete));
  EXPECT(ot_sz == 1 && ot_d[0] == 0 && complete == 1);

  mx_uint n_attr = 0;
  const char** attrs = nullptr;
  CHECK_OK(MXSymbolListAttrShallow(fc, &n_attr, &attrs));
  // flat key/value pairs; fc has num_hidden
  EXPECT(n_attr >= 2 && n_attr % 2 == 0);

  const char* pstr = nullptr;
  CHECK_OK(MXSymbolPrint(fc, &pstr));
  EXPECT(std::strstr(pstr, "FullyConnected") != nullptr);

  SymbolHandle* cut = nullptr;
  int cut_n = -1;
  CHECK_OK(MXSymbolCutSubgraph(fc, &cut, &cut_n));
  EXPECT(cut_n == 0);

  SymbolHandle gsym = nullptr;
  const char* wrt[] = {"fc1_weight"};
  CHECK_OK(MXSymbolGrad(fc, 1, wrt, &gsym));
  EXPECT(gsym != nullptr);

  // ---- SimpleBind / Reshape / BackwardEx / OptimizedSymbol ----------
  mx_uint n_args = 0, n_aux = 0;
  NDArrayHandle *arg_arr = nullptr, *grad_arr = nullptr, *aux_arr = nullptr;
  ExecutorHandle ex = nullptr;
  CHECK_OK(MXExecutorSimpleBind(fc, 1, 0, /*grad_req=*/1, 1, sk, ind_ptr,
                                sdata, &n_args, &arg_arr, &grad_arr,
                                &n_aux, &aux_arr, &ex));
  EXPECT(n_args == 3 && n_aux == 0);
  EXPECT(arg_arr[0] && arg_arr[1] && arg_arr[2]);
  EXPECT(grad_arr[0] && grad_arr[1] && grad_arr[2]);

  // fill data/weight/bias, forward, backward
  {
    size_t sizes[3] = {8 * 5, 4 * 5, 4};
    for (int i = 0; i < 3; ++i) {
      std::vector<float> buf(sizes[i], 0.1f);
      CHECK_OK(MXNDArraySyncCopyFromCPU(arg_arr[i], buf.data(), sizes[i]));
    }
  }
  CHECK_OK(MXExecutorForward(ex, 1));
  mx_uint n_outs = 0;
  NDArrayHandle* outs = nullptr;
  CHECK_OK(MXExecutorOutputs(ex, &n_outs, &outs));
  EXPECT(n_outs == 1);
  NDArrayHandle og = make_filled({8, 4}, 1.0f);
  NDArrayHandle ogs[] = {og};
  CHECK_OK(MXExecutorBackwardEx(ex, 1, ogs, 1));
  {
    std::vector<float> g(4 * 5, 0.f);
    CHECK_OK(MXNDArraySyncCopyToCPU(grad_arr[1], g.data(), g.size()));
    // dW = og^T x = 8 rows of 0.1 summed -> 0.8 each
    EXPECT(std::fabs(g[0] - 0.8f) < 1e-4);
  }

  const char* exstr = nullptr;
  CHECK_OK(MXExecutorPrint(ex, &exstr));
  EXPECT(std::strlen(exstr) > 0);

  SymbolHandle opt = nullptr;
  CHECK_OK(MXExecutorGetOptimizedSymbol(ex, &opt));
  EXPECT(opt != nullptr);

  mx_uint rs_ind[] = {0, 2};
  mx_uint rs_data[] = {16, 5};
  mx_uint rn_args = 0, rn_aux = 0;
  NDArrayHandle *r_args = nullptr, *r_grads = nullptr, *r_aux = nullptr;
  ExecutorHandle ex2 = nullptr;
  CHECK_OK(MXExecutorReshape(0, 1, ex, 1, sk, rs_ind, rs_data, &rn_args,
                             &r_args, &r_grads, &rn_aux, &r_aux, &ex2));
  EXPECT(rn_args == 3);
  {
    mx_uint nd2 = 0;
    const mx_uint* d2 = nullptr;
    CHECK_OK(MXNDArrayGetShape(r_args[0], &nd2, &d2));
    EXPECT(nd2 == 2 && d2[0] == 16 && d2[1] == 5);
  }

  // ---- BindX (empty group2ctx map == plain bind) ---------------------
  {
    NDArrayHandle bx_args[3];
    bx_args[0] = make_filled({8, 5}, 0.5f);
    bx_args[1] = make_filled({4, 5}, 0.5f);
    bx_args[2] = make_filled({4}, 0.0f);
    NDArrayHandle bx_grads[3] = {nullptr, nullptr, nullptr};
    mx_uint reqs[3] = {0, 0, 0};
    ExecutorHandle bex = nullptr;
    CHECK_OK(MXExecutorBindX(fc, 1, 0, 0, nullptr, nullptr, nullptr, 3,
                             bx_args, bx_grads, reqs, 0, nullptr, &bex));
    CHECK_OK(MXExecutorForward(bex, 0));
    CHECK_OK(MXExecutorFree(bex));
    for (auto h : bx_args) CHECK_OK(MXNDArrayFree(h));
  }

  // ---- NDArray extras ------------------------------------------------
  NDArrayHandle d1 = make_filled({2, 3}, 3.5f);
  void* pdata = nullptr;
  CHECK_OK(MXNDArrayGetData(d1, &pdata));
  EXPECT(pdata && static_cast<float*>(pdata)[0] == 3.5f);
  CHECK_OK(MXNDArrayWaitToWrite(d1));

  NDArrayHandle d2 = make_filled({2, 3}, 0.0f);
  CHECK_OK(MXNDArraySyncCopyFromNDArray(d2, d1, -1));
  {
    std::vector<float> buf(6, 0.f);
    CHECK_OK(MXNDArraySyncCopyToCPU(d2, buf.data(), 6));
    EXPECT(buf[5] == 3.5f);
  }
  NDArrayHandle d3 = make_filled({3}, 0.0f);
  CHECK_OK(MXNDArraySyncCopyFromNDArray(d3, d1, 1));  // row 1

  // save -> read file -> LoadFromBuffer round trip
  {
    NDArrayHandle pair[] = {d1, d2};
    const char* keys[] = {"alpha", "beta"};
    CHECK_OK(MXNDArraySave("/tmp/abi_r4_save.params", 2, pair, keys));
    FILE* f = std::fopen("/tmp/abi_r4_save.params", "rb");
    EXPECT(f != nullptr);
    std::fseek(f, 0, SEEK_END);
    long sz = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    std::vector<char> blob(sz);
    EXPECT(std::fread(blob.data(), 1, sz, f) == (size_t)sz);
    std::fclose(f);
    mx_uint n_loaded = 0, n_names = 0;
    NDArrayHandle* loaded = nullptr;
    const char** lnames = nullptr;
    CHECK_OK(MXNDArrayLoadFromBuffer(blob.data(), (size_t)sz, &n_loaded,
                                     &loaded, &n_names, &lnames));
    EXPECT(n_loaded == 2 && n_names == 2);
    EXPECT(std::string(lnames[0]) == "alpha");
    std::vector<float> buf(6, 0.f);
    CHECK_OK(MXNDArraySyncCopyToCPU(loaded[0], buf.data(), 6));
    EXPECT(buf[0] == 3.5f);
    for (mx_uint i = 0; i < n_loaded; ++i) CHECK_OK(MXNDArrayFree(loaded[i]));
  }

  // sparse create + format check; shared-mem must fail descriptively
  {
    mx_uint shp[] = {6, 4};
    int aux_t[] = {6};
    mx_uint aux_nd2[] = {1};
    mx_uint aux_shp[] = {0};
    NDArrayHandle rsp = nullptr;
    CHECK_OK(MXNDArrayCreateSparseEx(1, shp, 2, 1, 0, 0, 0, 1, aux_t,
                                     aux_nd2, aux_shp, &rsp));
    int st = -1;
    CHECK_OK(MXNDArrayGetStorageType(rsp, &st));
    EXPECT(st == 2);  // row_sparse code
    CHECK_OK(MXNDArraySyncCheckFormat(rsp, 1));
    int pid = 0, sid = 0;
    EXPECT_FAIL(MXNDArrayGetSharedMemHandle(rsp, &pid, &sid));
    EXPECT(std::strlen(MXGetLastError()) > 0);
    CHECK_OK(MXNDArrayFree(rsp));
  }

  // ---- KVStore extras ------------------------------------------------
  {
    KVStoreHandle kv = nullptr;
    CHECK_OK(MXKVStoreCreate("local", &kv));
    const char* kkeys[] = {"w0"};
    NDArrayHandle init_v[] = {make_filled({4}, 1.0f)};
    CHECK_OK(MXKVStoreInitEx(kv, 1, kkeys, init_v));
    NDArrayHandle pull_v[] = {make_filled({4}, 0.0f)};
    CHECK_OK(MXKVStorePullWithSparseEx(kv, 1, kkeys, pull_v, 0, 1));
    {
      std::vector<float> buf(4, 0.f);
      CHECK_OK(MXNDArraySyncCopyToCPU(pull_v[0], buf.data(), 4));
      EXPECT(buf[0] == 1.0f);
    }
    const char* ck[] = {"type", "threshold"};
    const char* cv[] = {"2bit", "0.5"};
    CHECK_OK(MXKVStoreSetGradientCompression(kv, 2, ck, cv));
    EXPECT_FAIL(MXKVStoreRunServer(kv, nullptr, nullptr));  // local store
    CHECK_OK(MXKVStoreSetBarrierBeforeExit(kv, 1));
    int dead = -1;
    CHECK_OK(MXKVStoreGetNumDeadNode(kv, -1, &dead));
    EXPECT(dead == 0);
    const char* ek[] = {"MXTPU_ABI_R4_TEST_ENV"};
    const char* ev[] = {"1"};
    CHECK_OK(MXInitPSEnv(1, ek, ev));
    CHECK_OK(MXNDArrayFree(init_v[0]));
    CHECK_OK(MXNDArrayFree(pull_v[0]));
    CHECK_OK(MXKVStoreFree(kv));
  }

  // ---- autograd extras ----------------------------------------------
  {
    NDArrayHandle w = make_filled({3}, 2.0f);
    NDArrayHandle g = make_filled({3}, 0.0f);
    NDArrayHandle vars[] = {w};
    NDArrayHandle grads[] = {g};
    CHECK_OK(MXAutogradMarkVariables(1, vars, grads));
    CHECK_OK(MXAutogradSetIsRecording(1, nullptr));
    NDArrayHandle sq = nullptr;
    {
      int n_out2 = 1;
      NDArrayHandle* outp = nullptr;
      NDArrayHandle ins[] = {w, w};
      CHECK_OK(MXImperativeInvoke("elemwise_mul", 2, ins, &n_out2, &outp,
                                  0, nullptr, nullptr));
      sq = outp[0];
    }
    CHECK_OK(MXAutogradSetIsRecording(0, nullptr));
    CHECK_OK(MXAutogradComputeGradient(1, &sq));
    {
      std::vector<float> buf(3, 0.f);
      CHECK_OK(MXNDArraySyncCopyToCPU(g, buf.data(), 3));
      EXPECT(std::fabs(buf[0] - 4.0f) < 1e-5);  // d(w*w)/dw = 2w
    }
    SymbolHandle as = nullptr;
    EXPECT_FAIL(MXAutogradGetSymbol(sq, &as));
    CHECK_OK(MXNDArrayFree(sq));
    CHECK_OK(MXNDArrayFree(w));
    CHECK_OK(MXNDArrayFree(g));
  }

  // ---- data-iter extras ----------------------------------------------
  {
    FILE* f = std::fopen("/tmp/abi_r4_data.csv", "w");
    EXPECT(f != nullptr);
    for (int i = 0; i < 8; ++i) std::fprintf(f, "%d.0,%d.0\n", i, i + 1);
    std::fclose(f);
    const char* dk[] = {"data_csv", "data_shape", "batch_size"};
    const char* dv[] = {"/tmp/abi_r4_data.csv", "(2,)", "4"};
    DataIterHandle it = nullptr;
    CHECK_OK(MXDataIterCreateIter("CSVIter", 3, dk, dv, &it));
    int has = 0;
    CHECK_OK(MXDataIterNext(it, &has));
    EXPECT(has == 1);
    uint64_t* idx = nullptr;
    uint64_t idx_n = 0;
    CHECK_OK(MXDataIterGetIndex(it, &idx, &idx_n));
    EXPECT(idx_n == 4 && idx[0] == 0);
    CHECK_OK(MXDataIterFree(it));

    const char* iname = nullptr;
    const char* idesc = nullptr;
    mx_uint inarg = 0;
    const char **inames, **itypes, **idescs;
    CHECK_OK(MXDataIterGetIterInfo("CSVIter", &iname, &idesc, &inarg,
                                   &inames, &itypes, &idescs));
    EXPECT(std::string(iname) == "CSVIter" && inarg > 0);
  }

  // ---- profile object ABI --------------------------------------------
  {
    ProfileHandle dom = nullptr, task = nullptr, frame = nullptr,
                  event = nullptr, counter = nullptr;
    CHECK_OK(MXProfileCreateDomain("abi_r4", &dom));
    CHECK_OK(MXProfileCreateTask(dom, "t", &task));
    CHECK_OK(MXProfileCreateFrame(dom, "f", &frame));
    CHECK_OK(MXProfileCreateEvent("e", &event));
    CHECK_OK(MXProfileCreateCounter(dom, "c", &counter));
    CHECK_OK(MXProfileDurationStart(task));
    CHECK_OK(MXProfileDurationStop(task));
    CHECK_OK(MXProfileDurationStart(event));
    CHECK_OK(MXProfileDurationStop(event));
    CHECK_OK(MXProfileSetCounter(counter, 7));
    CHECK_OK(MXProfileAdjustCounter(counter, -2));
    CHECK_OK(MXProfileSetMarker(dom, "mark", "process"));
    CHECK_OK(MXProfileDestroyHandle(task));
    CHECK_OK(MXProfileDestroyHandle(frame));
    CHECK_OK(MXProfileDestroyHandle(event));
    CHECK_OK(MXProfileDestroyHandle(counter));
    CHECK_OK(MXProfileDestroyHandle(dom));
  }

  // ---- quantization ABI ----------------------------------------------
  {
    SymbolHandle qsym = nullptr;
    CHECK_OK(MXQuantizeSymbol(fc, &qsym, 0, nullptr, 0, nullptr, "int8"));
    mx_uint qn = 0;
    const char** qargs = nullptr;
    CHECK_OK(MXSymbolListArguments(qsym, &qn, &qargs));
    bool has_q = false;
    for (mx_uint i = 0; i < qn; ++i)
      if (std::strstr(qargs[i], "weight")) has_q = true;
    EXPECT(has_q);
    const char* layer = "fc1_data_quantize";
    float mn = -1.0f, mx2 = 1.0f;
    SymbolHandle qsym2 = nullptr;
    CHECK_OK(MXSetCalibTableToQuantizedSymbol(qsym, 1, &layer, &mn, &mx2,
                                              &qsym2));
    EXPECT(qsym2 != nullptr);
    SymbolHandle backend_sym = nullptr;
    CHECK_OK(MXGenBackendSubgraph(fc, "MKLDNN", &backend_sym));
    CHECK_OK(MXSymbolFree(qsym));
    CHECK_OK(MXSymbolFree(qsym2));
    CHECK_OK(MXSymbolFree(backend_sym));
  }

  // ---- legacy Function registry --------------------------------------
  {
    mx_uint nf = 0;
    FunctionHandle* funcs = nullptr;
    CHECK_OK(MXListFunctions(&nf, &funcs));
    EXPECT(nf > 250);
    FunctionHandle relu = nullptr;
    CHECK_OK(MXGetFunction("relu", &relu));
    const char *fname, *fdesc, *rtype;
    mx_uint fnarg = 0;
    const char **fargn, **fargt, **fargd;
    CHECK_OK(MXFuncGetInfo(relu, &fname, &fdesc, &fnarg, &fargn, &fargt,
                           &fargd, &rtype));
    EXPECT(std::string(fname) == "relu");
    mx_uint nuse = 0, nsc = 0, nmut = 0;
    int mask = 0;
    CHECK_OK(MXFuncDescribe(relu, &nuse, &nsc, &nmut, &mask));
    EXPECT(nuse == 1 && nsc == 0 && nmut == 1);
    NDArrayHandle in = make_filled({4}, -1.5f);
    NDArrayHandle out = make_filled({4}, 9.0f);
    NDArrayHandle use_vars[] = {in};
    NDArrayHandle mut_vars[] = {out};
    CHECK_OK(MXFuncInvoke(relu, use_vars, nullptr, mut_vars));
    {
      std::vector<float> buf(4, 1.f);
      CHECK_OK(MXNDArraySyncCopyToCPU(out, buf.data(), 4));
      EXPECT(buf[0] == 0.0f);  // relu(-1.5) == 0
    }
    CHECK_OK(MXNDArrayFree(in));
    CHECK_OK(MXNDArrayFree(out));
  }

  // ---- runtime misc ---------------------------------------------------
  {
    const LibFeature* feats = nullptr;
    size_t nfeat = 0;
    CHECK_OK(MXLibInfoFeatures(&feats, &nfeat));
    bool has_cpu = false;
    for (size_t i = 0; i < nfeat; ++i)
      if (std::string(feats[i].name) == "CPU" && feats[i].enabled)
        has_cpu = true;
    EXPECT(has_cpu);
    CHECK_OK(MXSetNumOMPThreads(4));
    int prev = -1;
    CHECK_OK(MXEngineSetBulkSize(16, &prev));
    EXPECT(prev == 0);
    int fm = -1, tm = -1;
    CHECK_OK(MXGetGPUMemoryInformation(0, &fm, &tm));
    EXPECT(fm == 0 && tm == 0);
    uint64_t fm64 = 1, tm64 = 1;
    CHECK_OK(MXGetGPUMemoryInformation64(0, &fm64, &tm64));
    EXPECT(fm64 == 0 && tm64 == 0);
    void* rtc = nullptr;
    EXPECT_FAIL(MXRtcCudaModuleCreate("", 0, nullptr, 0, nullptr, &rtc));
    EXPECT(std::strstr(MXGetLastError(), "Pallas") != nullptr);
  }

  CHECK_OK(MXNDArrayFree(d1));
  CHECK_OK(MXNDArrayFree(d2));
  CHECK_OK(MXNDArrayFree(d3));
  CHECK_OK(MXNDArrayFree(og));
  CHECK_OK(MXExecutorFree(ex));
  CHECK_OK(MXExecutorFree(ex2));
  CHECK_OK(MXSymbolFree(grp));
  CHECK_OK(MXSymbolFree(children));
  CHECK_OK(MXSymbolFree(gsym));
  CHECK_OK(MXSymbolFree(opt));
  CHECK_OK(MXSymbolFree(fc));

  std::printf("ABI_R4_OK\n");
  return 0;
}
