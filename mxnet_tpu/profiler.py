"""Profiler (reference: ``python/mxnet/profiler.py:33-291`` over
``src/profiler/profiler.{h,cc}``).

Reference mechanism: engine worker threads wrap op execution in
``ProfileOperator`` spans, C-API calls get ``kAPI`` spans, storage hooks
record memory; output is chrome://tracing JSON plus aggregate per-op tables
(``aggregate_stats.cc``).

TPU-native redesign: there is no engine thread to instrument — XLA owns
device scheduling.  Two layers instead:

* **Host spans** — every imperative op dispatch (``ops/registry.invoke``),
  executor forward/backward, and user ProfileTask/Event/Frame objects are
  recorded wall-clock into an in-process buffer and dumped as
  chrome://tracing JSON (identical consumption story: load in
  ``chrome://tracing`` / Perfetto).  Aggregate per-op stats parity via
  :func:`dumps`.
* **Device traces** — ``set_config(tensorboard_dir=...)`` brackets the run
  with ``jax.profiler.start_trace/stop_trace`` (XLA's own profiler:
  per-HLO timing, HBM usage — the TPU analogue of the reference's kernel
  spans), and every op dispatch carries a ``jax.profiler.TraceAnnotation``
  so op names appear on the device timeline.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "state", "ProfileDomain", "Task", "Event",
           "Counter", "Frame", "Marker", "dispatch_count", "dispatch_stats",
           "dispatch_value", "record_span", "record_event", "now_us",
           "set_max_events", "recent_events"]

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": True,
    "aggregate_stats": False,
    "continuous_dump": False,
    "tensorboard_dir": None,
}
_state = "stop"
_paused = False
# chrome trace events — bounded ring (oldest dropped) so a week-long
# serving run with the profiler on cannot grow host memory without bound;
# all mutation goes through _append/_drain under _lock
_events = collections.deque()
_max_events = int(os.environ.get("MXNET_PROFILER_MAX_EVENTS", "1000000"))
_agg = {}          # name -> [count, total_us, min_us, max_us]
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def now_us():
    """Microseconds on the profiler's span timebase (for callers that
    time work themselves and report it via :func:`record_span`)."""
    return _now_us()


def set_max_events(n):
    """Resize the event ring (``MXNET_PROFILER_MAX_EVENTS`` sets the
    import-time default).  Shrinking drops the oldest events."""
    global _max_events
    n = int(n)
    if n < 1:
        raise ValueError("max events must be >= 1, got %d" % n)
    dropped = 0
    with _lock:
        _max_events = n
        while len(_events) > _max_events:
            _events.popleft()
            dropped += 1
    if dropped:
        _count_dropped(dropped)


def _count_dropped(n):
    from . import telemetry

    telemetry.registry().counter("profiler.events_dropped").inc(n)


def _append(evt):
    """Sole writer into the event ring: append under ``_lock`` (a
    concurrent :func:`dump` snapshot-and-clear can never lose or
    double-write events), evicting the oldest beyond the cap."""
    dropped = 0
    with _lock:
        while len(_events) >= _max_events:
            _events.popleft()
            dropped += 1
        _events.append(evt)
    if dropped:
        _count_dropped(dropped)


def recent_events(n=500):
    """Snapshot of the newest ``n`` chrome-trace events in the ring
    (postmortem debug bundles embed this; the ring itself is left
    untouched)."""
    n = max(0, int(n))
    with _lock:
        if n >= len(_events):
            return list(_events)
        return list(_events)[-n:]


def _active(category="imperative"):
    if _state != "run" or _paused:
        return False
    return bool(_config.get("profile_all")
                or _config.get("profile_" + category, True))


def record_span(name, cat, begin_us, dur_us, tid=None, args=None):
    """Append one complete ('X') chrome-trace span (internal hook API).
    No-op unless the profiler is running (so instrumented library code is
    free to leave Task/Frame objects in place)."""
    if _state != "run" or _paused:
        return
    evt = {"name": name, "cat": cat, "ph": "X",
           "ts": begin_us, "dur": dur_us, "pid": os.getpid(),
           "tid": tid if tid is not None
           else threading.get_ident() % 10000}
    if args:
        evt["args"] = args
    _append(evt)
    if _config.get("aggregate_stats"):
        with _lock:
            a = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
            a[0] += 1
            a[1] += dur_us
            a[2] = min(a[2], dur_us)
            a[3] = max(a[3], dur_us)


def record_event(evt):
    """Append one raw chrome-trace event (async 'b'/'e' flow events,
    instants, counters — whatever ``ph`` says).  Fills in ``ts``/``pid``/
    ``tid`` when absent; gated on the profiler running like every other
    recorder.  This is the channel mxnet_tpu.telemetry's request-trace
    helpers emit through."""
    if _state != "run" or _paused:
        return
    e = dict(evt)
    e.setdefault("ts", _now_us())
    e.setdefault("pid", os.getpid())
    e.setdefault("tid", threading.get_ident() % 10000)
    _append(e)


class _Span:
    """Context manager used by the framework hook points."""

    __slots__ = ("name", "cat", "begin", "_ann")

    def __init__(self, name, cat):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self.begin = _now_us()
        try:  # op names onto the XLA device timeline too
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
        record_span(self.name, self.cat, self.begin, _now_us() - self.begin)
        return False


def op_span(name):
    """Hook for ops/registry.invoke: a span when imperative profiling is
    live, else a no-op context."""
    if _active("imperative"):
        return _Span(name, "operator")
    return _NULL


def symbolic_span(name):
    if _active("symbolic"):
        return _Span(name, "symbolic")
    return _NULL


class _Null:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _Null()


# -- dispatch counters (always on, unlike spans) ----------------------------
# The donation-aware dispatch path (executor / _CachedOp / FusedTrainStep /
# ops.registry) reports cache behaviour here: "jit_cache_hit" /
# "jit_cache_miss" count calls that reused vs. (re)built a compiled
# executable at the step/graph level, "recompile" counts step-level traces,
# "op_recompile" counts per-op jit traces, "donated_bytes" accumulates the
# bytes of device buffers handed to XLA for in-place reuse, and
# "bucket_padded_batches" counts ragged batches padded up to a shape bucket.
# These live in the mxnet_tpu.telemetry registry as Counters under the
# "dispatch." prefix (no profiler session required) so CI can print them
# after every tier-1 run and any exporter can scrape them; when a profiler
# session IS running each update also lands as a chrome-trace counter event.
_DISPATCH_KEYS = ("jit_cache_hit", "jit_cache_miss", "recompile",
                  "op_recompile", "donated_bytes", "bucket_padded_batches",
                  "host_sync", "trace_guard",
                  # numerical-health sentinel + chaos harness
                  # (docs/NUMERICAL_HEALTH.md)
                  "nonfinite_steps", "rollbacks", "divergence_checks",
                  "faults_injected", "corrupt_records", "io_retries",
                  # overload-safe serving layer (docs/SERVING.md)
                  "requests_admitted", "requests_shed", "hedges_fired",
                  "breaker_trips", "batches_closed_by_deadline",
                  "requests_shed_brownout", "brownout_escalated",
                  "brownout_recovered",
                  # continuous-batching generative inference
                  # (docs/GENERATIVE.md)
                  "gen_prefills", "gen_decode_iters", "gen_tokens",
                  "gen_pages_shed", "gen_preempted", "gen_resumed",
                  "gen_brownout_shed",
                  # fleet layer: sharded replicas + autoscaling
                  # (docs/SHARDED_SERVING.md)
                  "fleet_replicas_added", "fleet_replicas_removed",
                  "fleet_scale_ups", "fleet_scale_downs",
                  "fleet_heartbeats", "fleet_heartbeats_dropped",
                  "fleet_reaped",
                  # cross-process fleet: gateway + worker supervision
                  # (docs/SHARDED_SERVING.md "Deployment")
                  "fleet_worker_restarts", "fleet_worker_crashes",
                  "fleet_worker_kills", "fleet_worker_beats",
                  "fleet_worker_beats_failed", "fleet_worker_requests",
                  "fleet_worker_idem_replays",
                  "gateway_requests", "gateway_retries",
                  "gateway_stream_lost", "gateway_stream_resumed",
                  "gateway_registry_errors",
                  # diagnosis plane (docs/OBSERVABILITY.md): cost-capture
                  # failures behind mfu_source fallbacks, and postmortem
                  # bundles written by the debug plane
                  "cost_analysis_failures", "debug_bundles")
_DISPATCH_PREFIX = "dispatch."


def _registry():
    from . import telemetry          # lazy: telemetry imports us back

    return telemetry.registry()


def dispatch_count(name, delta=1):
    """Bump a dispatch counter (internal hook API; unknown names are
    created on the fly so experiments don't need a registry edit)."""
    value = _registry().counter(_DISPATCH_PREFIX + name).inc(delta)
    if _state == "run" and not _paused:
        _append({"name": "dispatch::%s" % name, "cat": "counter",
                 "ph": "C", "ts": _now_us(), "pid": os.getpid(),
                 "args": {"value": value}})


def dispatch_value(name):
    """Current value of one dispatch counter (cheaper than a full
    dispatch_stats snapshot on the hot path)."""
    return _registry().counter(_DISPATCH_PREFIX + name).value


def dispatch_stats(reset=False):
    """Snapshot of the dispatch counters as a plain dict (all the
    well-known keys, zero-filled, plus any ad-hoc ones)."""
    from . import telemetry

    out = {k: 0 for k in _DISPATCH_KEYS}
    for full, metric in _registry().find(_DISPATCH_PREFIX):
        if not isinstance(metric, telemetry.Counter):
            continue
        key = full[len(_DISPATCH_PREFIX):]
        out[key] = metric.reset() if reset else metric.value
    return out


# -- public API (reference profiler.py surface) -----------------------------
def set_config(**kwargs):
    """Configure the profiler (reference :33).  Accepts the reference kwargs
    (filename, profile_all, profile_symbolic, profile_imperative,
    profile_memory, profile_api, aggregate_stats, continuous_dump) plus
    ``tensorboard_dir`` for XLA device traces."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise ValueError("unknown profiler options: %s" % sorted(unknown))
    _config.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    """'run' or 'stop' (reference :151)."""
    global _state
    assert state in ("run", "stop"), state
    if state == _state:
        return
    if state == "run":
        _maybe_start_device_trace()
    else:
        _maybe_stop_device_trace()
        if _config.get("continuous_dump"):
            dump()
    _state = state


def start():
    set_state("run")


def stop():
    set_state("stop")


def pause(profile_process="worker"):
    global _paused
    _paused = True


def resume(profile_process="worker"):
    global _paused
    _paused = False


def state():
    return _state


_device_trace_on = False


def _maybe_start_device_trace():
    global _device_trace_on
    tb = _config.get("tensorboard_dir")
    if tb:
        import jax
        jax.profiler.start_trace(tb)
        _device_trace_on = True


def _maybe_stop_device_trace():
    global _device_trace_on
    if _device_trace_on:
        import jax
        jax.profiler.stop_trace()
        _device_trace_on = False


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON to ``filename`` (reference :287,
    Profiler::DumpProfile).  ``finished=True`` (default) drains the event
    buffer so back-to-back profile sessions in one process don't
    accumulate (aggregate stats are kept; reset those via dumps)."""
    with _lock:
        payload = {"traceEvents": list(_events),
                   "displayTimeUnit": "ms"}
        if finished:
            _events.clear()
    with open(_config["filename"], "w") as f:
        json.dump(payload, f)


def dumps(reset=False, format="table"):
    """Aggregate per-op stats table (reference :291 over
    aggregate_stats.cc).  Requires ``set_config(aggregate_stats=True)``."""
    with _lock:
        rows = sorted(_agg.items(), key=lambda kv: -kv[1][1])
        out = ["%-40s %8s %12s %12s %12s %12s" %
               ("Name", "Calls", "Total(ms)", "Avg(ms)", "Min(ms)",
                "Max(ms)")]
        for name, (cnt, tot, mn, mx) in rows:
            out.append("%-40s %8d %12.3f %12.3f %12.3f %12.3f" %
                       (name, cnt, tot / 1e3, tot / cnt / 1e3, mn / 1e3,
                        mx / 1e3))
        if reset:
            _agg.clear()
    return "\n".join(out)


# -- object model (reference ProfileDomain/Task/Event/Counter/Frame) --------
class ProfileDomain:
    """Named grouping for profile objects (reference profiler.py Domain)."""

    def __init__(self, name):
        self.name = name


class Task:
    """A named span tied to a domain; start()/stop() (reference Task)."""

    _cat = "task"

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._begin = None

    def start(self):
        self._begin = _now_us()

    def stop(self):
        assert self._begin is not None, "%s not started" % self.name
        record_span("%s::%s" % (self.domain.name, self.name), self._cat,
                    self._begin, _now_us() - self._begin)
        self._begin = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class Event(Task):
    """Like Task but not domain-scoped per-thread (reference Event)."""

    _cat = "event"

    def __init__(self, name):
        self.domain = ProfileDomain("event")
        self.name = name
        self._begin = None


class Frame(Task):
    """Repeating frame span, e.g. one per training iteration."""

    _cat = "frame"


class Counter:
    """A named value tracked over time (reference Counter)."""

    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self._value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        self._value = value
        if _state != "run" or _paused:
            return
        _append({"name": "%s::%s" % (self.domain.name, self.name),
                 "cat": "counter", "ph": "C", "ts": _now_us(),
                 "pid": os.getpid(),
                 "args": {"value": value}})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


class Marker:
    """Instant event (reference Marker.mark)."""

    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if _state != "run" or _paused:
            return
        _append({"name": "%s::%s" % (self.domain.name, self.name),
                 "cat": "marker", "ph": "i", "ts": _now_us(),
                 "pid": os.getpid(), "s": scope[0]})


# ---------------------------------------------------------------------------
# XLA kernel-level attribution (below the op spans above): parse the
# chrome trace jax.profiler emits into per-HLO-category device time.
# Shared by bench.py's published breakdown and tools/profile_train.py.
# ---------------------------------------------------------------------------
def device_trace_events(trace_dir):
    """Device-lane events (with args) from the newest jax.profiler trace
    under ``trace_dir``."""
    import glob
    import gzip
    import json as _json

    traces = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")))
    if not traces:
        raise FileNotFoundError("no jax.profiler trace under %s"
                                % trace_dir)
    with gzip.open(traces[-1]) as f:
        tr = _json.load(f)
    dev_pids = {e["pid"] for e in tr["traceEvents"]
                if e.get("ph") == "M" and e.get("name") == "process_name"
                and "device:" in e["args"].get("name", "").lower()}
    return [e for e in tr["traceEvents"]
            if e.get("ph") == "X" and e.get("pid") in dev_pids
            and "args" in e]


def hlo_category_breakdown(trace_dir, steps=1):
    """{hlo_category: {ms_per_step, kernels, tflops, gb_s}} from a
    trace capturing ``steps`` executions."""
    agg = {}
    for e in device_trace_events(trace_dir):
        cat = e["args"].get("hlo_category")
        if not cat:
            continue
        d = agg.setdefault(cat, [0.0, 0, 0.0, 0.0])
        d[0] += e["dur"]
        d[1] += 1
        d[2] += float(e["args"].get("model_flops", 0) or 0)
        d[3] += float(e["args"].get("raw_bytes_accessed", 0) or 0)
    return {cat: {"ms_per_step": dur / 1e3 / steps,
                  "kernels": n // steps,
                  "tflops": dur and fl / (dur * 1e6) or 0.0,
                  "gb_s": dur and by / (dur * 1e3) or 0.0}
            for cat, (dur, n, fl, by) in agg.items()}
