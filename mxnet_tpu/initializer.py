"""Weight initializers.

Reference parity: ``python/mxnet/initializer.py`` (Xavier, MSRAPrelu,
Orthogonal, Bilinear, LSTMBias, Constant, Uniform, Normal, Mixed, Load,
InitDesc + registry).  TPU-native: initializers fill NDArrays via numpy on host
then device_put — initialization is one-time, so host compute is fine and keeps
the jit cache clean.
"""
from __future__ import annotations

import json
import re

import numpy as np

from . import ndarray as nd

__all__ = ["InitDesc", "Initializer", "register", "Zero", "One", "Constant",
           "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu",
           "Bilinear", "LSTMBias", "Mixed", "Load"]

_INIT_REGISTRY = {}


def register(klass):
    """Register an initializer under its lowercase class name
    (reference: ``mx.init.register``)."""
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (reference:
    ``initializer.py`` InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer; callable on (InitDesc/name, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")
        if init:
            if isinstance(init, Initializer):
                init._init_weight(desc, arr)
            elif isinstance(init, str) and init.lower() in _INIT_REGISTRY:
                _INIT_REGISTRY[init.lower()]()._init_weight(desc, arr)
            else:
                klass, kwargs = json.loads(init)
                _INIT_REGISTRY[klass.lower()](**kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- fill helpers ----------------------------------------------------
    @staticmethod
    def _set(arr, value):
        arr[:] = np.asarray(value, dtype=arr.dtype)

    def _init_zero(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, _, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_bias(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_gamma(self, _, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_beta(self, _, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_weight(self, name, arr):
        raise NotImplementedError("virtual _init_weight")

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to *weight/*bias/*gamma/*beta. Use "
            "mx.sym.Variable(init=mx.init.*) for other names" % name)

    def __repr__(self):
        return "%s(%s)" % (self.__class__.__name__, self._kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._set(arr, np.zeros(arr.shape))


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._set(arr, np.ones(arr.shape))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        if isinstance(self.value, nd.NDArray):
            self._set(arr, self.value.asnumpy())
        else:
            self._set(arr, np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, np.random.uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, np.random.normal(0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, (self.scale * q).reshape(arr.shape))


@register
class Xavier(Initializer):
    """Xavier/Glorot initialization (reference: ``initializer.py`` Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(
                "Xavier initializer cannot be applied to vector %s. It requires"
                " at least 2D." % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, np.random.uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, np.random.normal(0, scale, shape))
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He/MSRA initialization for PReLU nets."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for Deconvolution upsampling layers)."""

    def _init_weight(self, _, arr):
        weight = np.zeros(int(np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Initialize LSTM forget-gate bias to a constant, rest to zero."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype="float32")
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, b)


@register
class Mixed(Initializer):
    """Dispatch by regex on parameter name (reference: Mixed)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must match in length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(
            "Parameter name %s did not match any pattern. Consider adding a "
            '".*" pattern at the end with default Initializer.' % name)


@register
class Load(Initializer):
    """Initialize from a dict of loaded arrays, fallback to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            (k[4:] if k.startswith("arg:") or k.startswith("aux:") else k): v
            for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise ValueError(
                    "Parameter %s cannot be initialized from loading. Shape "
                    "mismatch, target %s vs loaded %s"
                    % (name, str(arr.shape), str(self.param[name].shape)))
            arr[:] = self.param[name].asnumpy() \
                if isinstance(self.param[name], nd.NDArray) else self.param[name]
        else:
            if self.default_init is None:
                raise ValueError(
                    "Cannot Initialize parameter %s. Not found in loaded param"
                    " and no default Initializer is provided." % name)
            self.default_init(name, arr)


def create(name, **kwargs):
    """Create an initializer from a registered name (or pass through)."""
    if isinstance(name, Initializer):
        return name
    return _INIT_REGISTRY[name.lower()](**kwargs)
