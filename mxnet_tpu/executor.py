"""Executor: a bound, compiled symbolic graph.

Reference parity: `Executor`/`GraphExecutor` (`src/executor/graph_executor.cc`
— Init:298 builds fwd+bwd graph, plans memory, creates cached engine ops;
Forward/Backward :64-92; `simple_bind`:1626).  TPU-native redesign (the
north-star in BASELINE.json): no nnvm passes, no memory planner, no cached
opr segments — the WHOLE graph lowers to ONE `jax.jit` XLA module per
(train, shape) key, and the backward graph is `jax.vjp` over that same pure
function (fused fwd+bwd module on the training path).  XLA does scheduling,
fusion, rematerialization, and memory planning — the jobs of
`GraphExecutor::Init`.

Aux states (BatchNorm running stats) are explicit carried outputs written
back after each call — the functional version of the reference's mutable aux
arrays.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import ndarray as nd
from .ndarray import NDArray
from .context import current_context

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx=None, grad_req="write", arg_shapes=None,
                 args=None, args_grad=None, aux_states=None, type_dict=None,
                 group2ctx=None, shared_exec=None, dp_args=None):
        self._symbol = symbol
        # data parallelism over a context LIST (reference:
        # DataParallelExecutorGroup, module/executor_group.py:143 — batch
        # split across contexts, per-device executor replicas, gradient
        # reduce via kvstore).  TPU-native redesign: ONE SPMD module over
        # a ("dp",) device mesh — args named in `dp_args` (the data/label
        # inputs) are sharded on their batch dim, params are replicated,
        # and XLA's partitioner inserts the gradient all-reduce the
        # reference routed through kvstore push/pull.
        ctx_list = list(ctx) if isinstance(ctx, (list, tuple)) else None
        self._ctx = (ctx_list[0] if ctx_list else ctx) or current_context()
        self._ctx_list = ctx_list  # preserved across reshape()
        self._dp_devs = ([c.jax_device() for c in ctx_list]
                         if ctx_list and len(ctx_list) > 1 else None)
        self._dp_args = set(dp_args or ()) if self._dp_devs else set()
        # model-parallel placement (reference AssignContext,
        # graph_executor.cc:909-915): nodes stamped `__ctx_group__` (via
        # mx.AttrScope(ctx_group=...)) are pinned to group2ctx[group]'s
        # device; XLA inserts the cross-device copies the reference added
        # as explicit copy nodes (:1380-1384).  Unmapped groups fall back
        # to the default ctx, like the reference.
        self._node_device = {}
        if group2ctx:
            for node in symbol._topo():
                grp = (node.user_attrs or {}).get("__ctx_group__")
                if grp and grp in group2ctx:
                    dev = group2ctx[grp].jax_device()
                    if dev != self._ctx.jax_device():
                        self._node_device[id(node)] = dev
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()
        self._n_out = len(symbol._outputs)

        # grad_req normalization: str | list | dict  (reference executor)
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self.arg_names}

        # -- materialize arg/aux arrays --------------------------------
        if args is not None:
            self.arg_arrays = self._align(args, self.arg_names, "args")
        else:
            shapes = dict(arg_shapes or {})
            inferred, _, aux_inferred = symbol.infer_shape(**shapes)
            self.arg_arrays = []
            for name, shp in zip(self.arg_names, inferred):
                if shp is None:
                    raise ValueError(
                        "cannot infer shape of argument %r — pass its shape "
                        "to simple_bind" % name)
                dt = (type_dict or {}).get(name, np.float32)
                self.arg_arrays.append(nd.zeros(shp, dtype=dt, ctx=self._ctx))
        if aux_states is not None:
            self.aux_arrays = self._align(aux_states, self.aux_names, "aux")
        else:
            shapes = {n: a.shape for n, a in zip(self.arg_names,
                                                 self.arg_arrays)}
            _, _, aux_inferred = symbol.infer_shape(**shapes)
            self.aux_arrays = []
            for name, shp in zip(self.aux_names, aux_inferred):
                if shp is None:
                    raise ValueError("cannot infer aux shape %r" % name)
                self.aux_arrays.append(nd.zeros(shp, ctx=self._ctx))

        # -- gradient buffers ------------------------------------------
        if args_grad is not None:
            self.grad_arrays = self._align(args_grad, self.arg_names,
                                           "args_grad", allow_missing=True)
        else:
            self.grad_arrays = [
                nd.zeros(a.shape, dtype=a.dtype, ctx=self._ctx)
                if self._grad_req.get(n, "null") != "null" else None
                for n, a in zip(self.arg_names, self.arg_arrays)]

        self.arg_dict = dict(zip(self.arg_names, self.arg_arrays))
        self.aux_dict = dict(zip(self.aux_names, self.aux_arrays))
        self.grad_dict = dict(zip(self.arg_names, self.grad_arrays))
        self.outputs = []
        self._fn_cache = {}
        self._is_train = False
        self._monitor_cb = None
        self._monitor_fn_cache = {}

    def _align(self, values, names, what, allow_missing=False):
        if isinstance(values, dict):
            out = []
            for n in names:
                if n in values:
                    v = values[n]
                    out.append(v if isinstance(v, NDArray) else nd.array(v))
                elif allow_missing:
                    out.append(None)
                else:
                    raise ValueError("missing %s entry %r" % (what, n))
            return out
        values = [v if (v is None or isinstance(v, NDArray)) else nd.array(v)
                  for v in values]
        if len(values) != len(names):
            raise ValueError("%s length %d != expected %d"
                             % (what, len(values), len(names)))
        return list(values)

    # ------------------------------------------------------------------
    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-node output tap (reference
        ``MXExecutorSetMonitorCallbackEX`` / ``ExecuteMonInputCallback``,
        graph_executor.cc:1295,1375).  While installed, ``forward`` runs
        the graph eagerly node-by-node (outside jit) so every
        intermediate is observable — the debugging trade the reference
        also makes when a monitor is attached."""
        self._monitor_cb = callback
        self._monitor_fn_cache = {}

    def _graph_fn(self, train, tap=None):
        """Pure function (rng, arg_list, aux_list) -> (outs..., new_auxs...)
        — the single XLA module (or, with ``tap``, the eager monitored
        interpretation)."""
        sym = self._symbol
        topo = sym._topo()
        arg_index = {n: i for i, n in enumerate(self.arg_names)}
        aux_index = {n: i for i, n in enumerate(self.aux_names)}
        # count rng consumers for key splitting
        rng_ops = [node for node in topo
                   if not node.is_var and node.op.needs_rng]

        node_device = self._node_device

        def fn(rng, arg_vals, aux_vals):
            env = {}
            new_aux = dict(enumerate(aux_vals))
            keys = (list(jax.random.split(rng, len(rng_ops)))
                    if rng_ops else [])
            ki = 0
            for node in topo:
                if node.is_var:
                    if node.name in arg_index:
                        env[id(node)] = (arg_vals[arg_index[node.name]],)
                    else:
                        env[id(node)] = (aux_vals[aux_index[node.name]],)
                    continue
                ins = [env[id(src)][oi] for src, oi in node.inputs]
                dev = node_device.get(id(node))
                if dev is not None:  # group2ctx placement
                    ins = [jax.device_put(x, dev) for x in ins]
                f = node.op.bind(dict(node.attrs), train)
                if node.op.needs_rng:
                    res = f(keys[ki], *ins)
                    ki += 1
                else:
                    res = f(*ins)
                if not isinstance(res, (tuple, list)):
                    res = (res,)
                env[id(node)] = tuple(res)
                if tap is not None:
                    tap(node.name, res)
                # aux write-back (FMutateInputs parity)
                for out_i, in_i in node.op.mutate.items():
                    if in_i < len(node.inputs):
                        src, _ = node.inputs[in_i]
                        if src.is_var and src.name in aux_index:
                            new_aux[aux_index[src.name]] = res[out_i]
            outs = tuple(env[id(n)][oi] for n, oi in sym._outputs)
            return outs, tuple(new_aux[i] for i in range(len(aux_vals)))

        return fn

    def _compiled(self, kind, train):
        from . import dispatch as _dispatch

        # donate the aux-state inputs (BN running stats) on the fused
        # fwd+bwd kinds: their pre-step value is dead after the call (the
        # returned new_aux is written back), so XLA may update them
        # in-place in device memory.  Forward never donates — its aux
        # snapshot (_aux_in) must survive for the paired backward.  dp
        # resharding device_puts fresh arrays anyway, so skip there.
        donate_aux = (kind in ("backward", "backward_ones")
                      and bool(self.aux_names)
                      and not self._dp_devs
                      and _dispatch.donation_active())
        key = (kind, train, donate_aux,
               tuple(a.shape + (str(a.dtype),) for a in self.arg_arrays))
        f = self._fn_cache.get(key)
        if f is not None:
            return f
        graph_fn = self._graph_fn(train)
        n_out = self._n_out
        grad_pos = [i for i, n in enumerate(self.arg_names)
                    if self._grad_req.get(n, "null") != "null"]
        donate = (2,) if donate_aux else ()

        if kind == "forward":
            def run(rng, args, auxs):
                return graph_fn(rng, args, auxs)
            f = _dispatch.TrackedJit(run, label="Executor.forward")
        elif kind in ("backward", "backward_ones"):
            # fused fwd+bwd: one XLA module for the whole training step's
            # compute (reference: full fwd+bwd graph in GraphExecutor::Init).
            # "backward_ones" builds the head gradients as ones_like(outs)
            # inside the module, so no standalone forward is needed first.
            def run(rng, args, auxs, head_grads):
                def fwd(diff_args):
                    full = list(args)
                    for p, v in zip(grad_pos, diff_args):
                        full[p] = v
                    outs, new_aux = graph_fn(rng, full, auxs)
                    return outs, new_aux
                diff = [args[p] for p in grad_pos]
                (outs, new_aux), vjp = jax.vjp(lambda d: fwd(d), diff)
                heads = (tuple(jnp.ones_like(o) for o in outs)
                         if head_grads is None else tuple(head_grads))
                (grads,) = vjp((heads,
                                tuple(jnp.zeros_like(a) for a in new_aux)))
                return outs, new_aux, grads
            if kind == "backward":
                f = _dispatch.TrackedJit(run, donate_argnums=donate,
                                         label="Executor.backward")
            else:
                f = _dispatch.TrackedJit(
                    lambda rng, args, auxs: run(rng, args, auxs, None),
                    donate_argnums=donate, label="Executor.backward_ones")
        else:
            raise ValueError(kind)
        inner = f
        if self._dp_devs and self._dp_args:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            mesh = Mesh(np.array(self._dp_devs), ("dp",))
            shard = NamedSharding(mesh, PartitionSpec("dp"))
            repl = NamedSharding(mesh, PartitionSpec())
            dp_idx = {i for i, n in enumerate(self.arg_names)
                      if n in self._dp_args}

            def f(rng, args, auxs, *rest, _inner=inner):
                args = [jax.device_put(a, shard if i in dp_idx else repl)
                        for i, a in enumerate(args)]
                auxs = [jax.device_put(a, repl) for a in auxs]
                # head gradients (the "backward" kind's extra arg) match
                # the outputs' batch-sharded layout
                rest = tuple(
                    tuple(jax.device_put(h, shard) if h is not None
                          else None for h in r)
                    if isinstance(r, (tuple, list)) else r for r in rest)
                return _inner(rng, args, auxs, *rest)
        elif self._sharded_mesh() is not None:
            # pjit-sharded params (serving mesh-slice replicas,
            # docs/SHARDED_SERVING.md): the bound weights are committed
            # multi-device arrays, so the module already runs across the
            # slice.  Every single-device operand — the rng key, staged
            # request inputs, unsharded params — must be replicated onto
            # the slice's mesh or jit rejects the mixed committed device
            # sets; always-replicated inputs also keep the compile cache
            # keys constant, so a warmed server never recompiles.
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self._sharded_mesh(), PartitionSpec())

            def place(x, _repl=repl):
                try:
                    if len(x.sharding.device_set) > 1:
                        return x
                except (AttributeError, TypeError):
                    pass
                return jax.device_put(x, _repl)

            def f(rng, args, auxs, *rest, _inner=inner, _place=place):
                return _inner(_place(rng), [_place(a) for a in args],
                              [_place(a) for a in auxs], *rest)
        else:
            # pin execution to the bound context's device: without this a
            # cpu()-bound executor on a TPU host runs under the default
            # (TPU) device and its outputs silently migrate the arg
            # arrays there
            dev = self._ctx.jax_device()

            def f(*a, _inner=inner, _dev=dev):
                with jax.default_device(_dev):
                    return _inner(*a)

        self._fn_cache[key] = f
        return f

    # ------------------------------------------------------------------
    def _sharded_mesh(self):
        """The mesh of any multi-device bound array (pjit-sharded mode),
        else None.  Evaluated at compile-wrapper build time — sharding is
        applied right after bind, before the first forward."""
        for a in list(self.arg_arrays) + list(self.aux_arrays):
            try:
                sh = a.data.sharding
                if len(sh.device_set) > 1:
                    return sh.mesh
            except (AttributeError, TypeError):
                continue
        return None

    def _devolve(self, vals):
        """Under dp: move mesh-replicated results to the primary device."""
        if not self._dp_devs:
            return vals
        prim = self._ctx.jax_device()
        return tuple(jax.device_put(v, prim) for v in vals)

    def _stage(self, feed):
        """Write a {name: array} feed into the bound arg arrays."""
        for k, v in feed.items():
            if k not in self.arg_dict:
                raise ValueError("unknown argument %r" % k)
            data = v.data if isinstance(v, NDArray) else jnp.asarray(v)
            self.arg_dict[k]._set_data(data)

    def forward(self, is_train=False, **kwargs):
        from . import profiler as _prof
        with _prof.symbolic_span("Executor::forward"):
            return self._forward_impl(is_train, **kwargs)

    def _forward_impl(self, is_train=False, **kwargs):
        from . import random as _random

        self._stage(kwargs)
        self._is_train = bool(is_train)
        if self._monitor_cb is not None:
            fn = self._monitor_fn_cache.get(self._is_train)
            if fn is None:
                inner = self._graph_fn(self._is_train,
                                       tap=self._monitor_cb)
                dev = self._ctx.jax_device()

                def fn(*a, _inner=inner, _dev=dev):
                    # same context pin as the compiled path — observation
                    # must not move the computation to another device
                    with jax.default_device(_dev):
                        return _inner(*a)

                self._monitor_fn_cache[self._is_train] = fn
        else:
            fn = self._compiled("forward", self._is_train)
        rng = _random.next_key()
        aux_in = [a.data for a in self.aux_arrays]
        outs, new_aux = fn(rng, [a.data for a in self.arg_arrays], aux_in)
        new_aux = self._devolve(new_aux)
        self._last_rng = rng
        # snapshot pre-update aux: a following backward() recomputes the
        # forward from this same starting state, so aux EMA (BatchNorm
        # moving stats) is applied exactly once per fwd+bwd pair
        self._aux_in = aux_in
        for arr, val in zip(self.aux_arrays, new_aux):
            arr._set_data(val)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    def backward(self, out_grads=None, is_train=True, **kwargs):
        from . import profiler as _prof
        with _prof.symbolic_span("Executor::backward"):
            return self._backward_impl(out_grads, is_train, **kwargs)

    def _backward_impl(self, out_grads=None, is_train=True, **kwargs):
        from . import random as _random

        self._stage(kwargs)
        if out_grads is None:
            head_grads = [None] * self._n_out
        elif isinstance(out_grads, NDArray):
            head_grads = [out_grads.data] + [None] * (self._n_out - 1)
        else:
            head_grads = [g.data if isinstance(g, NDArray) else
                          (jnp.asarray(g) if g is not None else None)
                          for g in out_grads]
        rng = getattr(self, "_last_rng", None)
        if rng is None:
            rng = _random.next_key()
        self._last_rng = None  # consume: each fwd+bwd pair gets fresh keys
        # aux inputs: recompute from the pre-forward snapshot when a forward
        # already ran this step (single EMA application per fwd+bwd pair)
        aux_in = getattr(self, "_aux_in", None)
        if aux_in is None:
            aux_in = [a.data for a in self.aux_arrays]
        self._aux_in = None
        arg_data = [a.data for a in self.arg_arrays]
        if all(g is None for g in head_grads):
            # head grads of ones built inside the jitted module — no
            # standalone forward needed
            fn = self._compiled("backward_ones", True)
            outs, new_aux, grads = fn(rng, arg_data, aux_in)
        else:
            # mixed None/concrete heads need output shapes for the ones
            concrete_heads = []
            if any(g is None for g in head_grads):
                if not self.outputs:
                    self.forward(is_train=True)
                    aux_in = self._aux_in
                    self._aux_in = None
                    # the probe forward re-armed _last_rng; this fwd+bwd
                    # pair's key is already consumed above
                    self._last_rng = None
                for o, g in zip(self.outputs, head_grads):
                    concrete_heads.append(
                        g if g is not None else jnp.ones(o.shape, o.dtype))
            else:
                concrete_heads = head_grads
            fn = self._compiled("backward", True)
            outs, new_aux, grads = fn(rng, arg_data, aux_in,
                                      tuple(concrete_heads))
        # under dp, grads/aux are mesh-replicated; bring them home to the
        # primary device so the (single-device) optimizer kernels and any
        # imperative follow-up ops see ordinary committed arrays — the
        # replicated layout makes this a local shard fetch, not a gather
        grads = self._devolve(grads)
        new_aux = self._devolve(new_aux)
        grad_pos = [i for i, n in enumerate(self.arg_names)
                    if self._grad_req.get(n, "null") != "null"]
        for p, g in zip(grad_pos, grads):
            tgt = self.grad_arrays[p]
            if tgt is None:
                continue
            name = self.arg_names[p]
            if self._grad_req.get(name) == "add":
                tgt._set_data(tgt.data + g)
            else:
                tgt._set_data(g)
        for arr, val in zip(self.aux_arrays, new_aux):
            arr._set_data(val)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    # ------------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(
                    arr.data if isinstance(arr, NDArray)
                    else jnp.asarray(arr))
            elif not allow_extra_params:
                raise ValueError("unknown arg %r" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._set_data(
                        arr.data if isinstance(arr, NDArray)
                        else jnp.asarray(arr))
                elif not allow_extra_params:
                    raise ValueError("unknown aux %r" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new shapes (cheap: jit recompiles per shape key)."""
        shapes = {n: a.shape for n, a in self.arg_dict.items()}
        shapes.update(kwargs)
        new = Executor(self._symbol, ctx=self._ctx_list or self._ctx,
                       grad_req=self._grad_req, arg_shapes=shapes,
                       dp_args=self._dp_args)
        for n, a in self.arg_dict.items():
            if new.arg_dict[n].shape == a.shape:
                new.arg_dict[n]._set_data(a.data)
        for n, a in self.aux_dict.items():
            if new.aux_dict[n].shape == a.shape:
                new.aux_dict[n]._set_data(a.data)
        return new

    def debug_str(self):
        return "Executor(%d nodes)" % len(self._symbol._topo())
