"""Checkpoint helpers (symbolic format).

Reference parity: `python/mxnet/model.py` — `save_checkpoint` (:394) writes
``prefix-symbol.json`` (graph JSON) + ``prefix-####.params`` (NDArray map
with ``arg:``/``aux:`` key prefixes), `load_checkpoint` (:424).  Formats are
kept shape-compatible: the params file is `nd.save`'s container and the
symbol file is the nnvm-shaped JSON from `Symbol.tojson`.
"""
from __future__ import annotations

from . import ndarray as nd

__all__ = ["save_checkpoint", "load_checkpoint", "BatchEndParam"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """Returns (symbol, arg_params, aux_params)."""
    from .symbol import load as sym_load

    symbol = sym_load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


from .module.base_module import BatchEndParam  # noqa: E402,F401
