"""Flagship end-to-end models, built TPU-first.

The reference's model corpus lives in `example/image-classification/symbols/`
and `python/mxnet/gluon/model_zoo/` (vision CNNs — mirrored in
``mxnet_tpu.gluon.model_zoo``) plus `example/rnn/word_lm` (LSTM LM).  This
package holds the pure-JAX flagship models used for benchmarking and the
multi-chip parallelism demos: sharding-native transformer LM (dp/fsdp/tp/sp/
ep/pp), the scale class the reference never reached.
"""
from .transformer import (TransformerLM, TransformerConfig,  # noqa: F401
                          make_train_step)
