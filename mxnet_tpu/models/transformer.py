"""Sharding-native Transformer language model — the flagship multi-chip model.

Everything the reference lacked for long-context/distributed (SURVEY.md §2.4,
§5): one model covering data parallel (``dp``), ZeRO-style parameter sharding
(``fsdp``), tensor parallel (``tp`` — Megatron-style column/row splits
expressed *declaratively* as GSPMD shardings, XLA inserts the collectives),
sequence parallel via ring attention (``sp``, `parallel/ring_attention.py`),
expert parallel MoE (``ep``, `parallel/moe.py`), and a GPipe pipeline variant
(``pp``, `parallel/pipeline.py`).

Design notes (TPU-first):
* parameters are a flat ``{name: jax.Array}`` dict; layer stacks use a leading
  ``L`` dim + ``lax.scan`` over blocks (ONE traced block body, remat-friendly)
  — not L separately-traced python layers.  By default the scan is UNROLLED
  at compile time (``scan_unroll=True``: XLA overlaps across layers, measured
  47.4%→53.7% MFU) at a compile-time cost ~ n_layers; deep configs can set
  ``scan_unroll=False`` to regain one-body compiles;
* compute dtype bf16, accumulation f32 (MXU-native);
* causal LM loss is computed from sharded logits; everything is static-shaped.
"""
from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.sharding import ShardingRules, constraint, PartitionSpec as P
from ..parallel.ring_attention import ring_self_attention, blockwise_attention
from ..parallel.moe import moe_layer

__all__ = ["TransformerConfig", "TransformerLM", "make_train_step",
           "default_rules"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_len: int = 2048
    dtype: str = "bfloat16"
    use_moe: bool = False
    n_experts: int = 8
    moe_aux_weight: float = 0.01
    remat: bool = True
    # Unroll the layer scan: one traced body, unrolled execution — XLA
    # overlaps/fuses across layers (measured on v5e: 47.4% -> 53.7% MFU
    # for the d2048x4 flagship; scan bodies ran at ~22 TF/s vs 120-190
    # for the same kernels unrolled).  Costs compile time ~ n_layers.
    scan_unroll: bool = True
    # Small attention problems use plain dense attention (scores
    # materialize, but the fused matmul+softmax runs at full MXU rate:
    # measured 60.0% vs 53.7% MFU with the Pallas flash kernel at
    # B=8/H=16/T=1024); bigger ones switch to flash so memory stays
    # O(T).  The gate is the f32 score-tensor size B*H*T^2*4 bytes —
    # gating on T alone would let large batches OOM.
    dense_attn_max_score_mb: int = 768

    @property
    def head_dim(self):
        return self.d_model // self.n_heads


def default_rules() -> ShardingRules:
    """Megatron/FSDP layout: attention qkv + MLP-up are column-parallel (tp on
    the output feature), proj + MLP-down row-parallel (tp on the input
    feature); fsdp shards the other big dim; MoE experts shard on ep."""
    return ShardingRules([
        (r"embed",        P("tp", "fsdp")),
        (r".*wqkv",       P(None, "fsdp", "tp")),
        (r".*wo",         P(None, "tp", "fsdp")),
        (r".*w_up",       P(None, "fsdp", "tp")),
        (r".*w_down",     P(None, "tp", "fsdp")),
        (r".*moe_up",     P(None, "ep", "fsdp", None)),
        (r".*moe_down",   P(None, "ep", None, "fsdp")),
        (r".*gate",       P(None, "fsdp", None)),
        (r"unembed",      P("fsdp", "tp")),
        (r".*",           P()),
    ])


def _dense_self_attention(q, k, v, causal=True):
    """Plain materialized attention for short sequences: on TPU the fused
    QK^T -> softmax -> PV chain runs at full MXU rate (measured 60% MFU
    for the flagship at T=1024 vs 53.7% with the flash kernel); memory is
    O(T^2) so the caller gates it by ``dense_attn_max_score_mb``."""
    B, T, H, D = q.shape
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                   preferred_element_type=jnp.float32) / math.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    return o.transpose(0, 2, 1, 3)


class TransformerLM:
    """Decoder-only LM.  Methods are pure functions over a flat param dict."""

    def __init__(self, config: TransformerConfig):
        self.cfg = config

    # -- init ----------------------------------------------------------
    def init(self, rng) -> dict:
        cfg = self.cfg
        L, E, F = cfg.n_layers, cfg.d_model, cfg.d_ff
        HD = cfg.n_heads * cfg.head_dim
        dt = jnp.dtype(cfg.dtype)
        keys = jax.random.split(rng, 8)

        def norm(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    / math.sqrt(fan_in)).astype(dt)

        p = {
            "embed": norm(keys[0], (cfg.vocab_size, E), E),
            "blocks.ln1_scale": jnp.ones((L, E), dt),
            "blocks.ln2_scale": jnp.ones((L, E), dt),
            "blocks.wqkv": norm(keys[1], (L, E, 3 * HD), E),
            "blocks.wo": norm(keys[2], (L, HD, E), HD),
            "final_ln_scale": jnp.ones((E,), dt),
            "unembed": norm(keys[3], (E, cfg.vocab_size), E),
        }
        if cfg.use_moe:
            p["blocks.gate"] = norm(keys[4], (L, E, cfg.n_experts), E)
            p["blocks.moe_up"] = norm(keys[5], (L, cfg.n_experts, E, F), E)
            p["blocks.moe_down"] = norm(keys[6], (L, cfg.n_experts, F, E), F)
        else:
            p["blocks.w_up"] = norm(keys[5], (L, E, F), E)
            p["blocks.w_down"] = norm(keys[6], (L, F, E), F)
        return p

    # -- forward -------------------------------------------------------
    def _rmsnorm(self, x, scale):
        # kernel registry (docs/KERNELS.md): fused Pallas kernel (one VMEM
        # pass) on single-chip TPU or under MXTPU_PALLAS=interpret; under a
        # mesh GSPMD can't partition the custom call, and the lax form
        # below fuses fine anyway
        from ..ops.pallas.common import select_impl
        fn, impl = select_impl("fused_rmsnorm")
        if impl in ("pallas", "interpret"):
            return fn(x, scale.astype(x.dtype))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6)
                ).astype(x.dtype) * scale

    def _block(self, bp, x, use_ring, with_kv=False):
        cfg = self.cfg
        B, T, E = x.shape
        H, D = cfg.n_heads, cfg.head_dim
        h = self._rmsnorm(x, bp["ln1_scale"])
        qkv = jnp.einsum("bte,ef->btf", h, bp["wqkv"],
                         preferred_element_type=jnp.float32).astype(x.dtype)
        qkv = constraint(qkv, "dp", "sp", "tp")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        score_mb = B * H * T * T * 4 / 1e6
        if use_ring:
            attn = ring_self_attention(q, k, v, causal=True)
        else:
            # kernel registry (docs/KERNELS.md): 'pallas'/'sharded' is the
            # flash kernel (dense-gated below — small problems run the
            # materialized form at full MXU rate), 'interpret' forces the
            # real kernels through the interpreter regardless of size (the
            # parity-testing mode), 'fallback' is the lax blockwise path.
            from ..ops.pallas.common import select_impl
            attn_fn, attn_impl = select_impl("flash_attention")
            if attn_impl == "interpret":
                attn = attn_fn(q, k, v, causal=True)
            elif score_mb <= cfg.dense_attn_max_score_mb:
                attn = _dense_self_attention(q, k, v, causal=True)
            elif attn_impl in ("pallas", "sharded"):
                attn = attn_fn(q, k, v, causal=True)
            else:
                attn = blockwise_attention(q, k, v, causal=True)
        attn = attn.reshape(B, T, H * D)
        o = jnp.einsum("btf,fe->bte", attn, bp["wo"],
                       preferred_element_type=jnp.float32).astype(x.dtype)
        x = x + constraint(o, "dp", "sp", None)

        h = self._rmsnorm(x, bp["ln2_scale"])
        aux = jnp.float32(0.0)
        if cfg.use_moe:
            ff, aux = moe_layer(h, bp["gate"], bp["moe_up"], bp["moe_down"])
        else:
            up = jnp.einsum("bte,ef->btf", h, bp["w_up"],
                            preferred_element_type=jnp.float32)
            up = constraint(jax.nn.gelu(up).astype(x.dtype), "dp", "sp", "tp")
            ff = jnp.einsum("btf,fe->bte", up, bp["w_down"],
                            preferred_element_type=jnp.float32).astype(x.dtype)
        x = x + constraint(ff, "dp", "sp", None)
        if with_kv:
            return x, aux, k, v
        return x, aux

    # -- generative decode (paged KV cache) ----------------------------
    #
    # Layout: k_pages / v_pages are [L, P, page_size, H, D] in the model
    # dtype.  Page 0 is the reserved GARBAGE page: writes from prompt
    # padding and inactive decode slots are routed there unconditionally,
    # so neither function ever branches on validity — the attention mask
    # (position <= length) is the only consumer-side filter, and stale
    # garbage never leaks into logits.  Per-sequence page tables are
    # [M] int32 (M = max pages per sequence) padded with 0; position t of
    # a sequence lives at flat slot page_table[t // ps] * ps + t % ps.
    # The allocator/scheduler around these functions lives in
    # mxnet_tpu/generation.py (docs/GENERATIVE.md).

    def init_kv_pages(self, num_pages, page_size):
        """Allocate zeroed paged KV storage: ([L,P,ps,H,D], same) pair."""
        cfg = self.cfg
        shape = (cfg.n_layers, num_pages, page_size, cfg.n_heads,
                 cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    def prefill(self, params, k_pages, v_pages, tokens, length, page_table):
        """Run the prompt through the model, writing per-layer K/V into the
        paged cache and returning next-token logits.

        tokens: [1, Tpad] int32 (prompt left-aligned, padded to a shape
        bucket); length: scalar int32, true prompt length (traced — one
        compile per Tpad bucket, not per length); page_table: [M] int32,
        pages backing positions 0..length-1.  Returns
        (k_pages, v_pages, logits [V] f32) where logits are taken at
        position length-1 (the next-token distribution — TTFT comes from
        argmax of this, no decode step needed for the first token).
        """
        cfg = self.cfg
        if cfg.use_moe:
            raise NotImplementedError("paged decode does not support MoE yet")
        ps = k_pages.shape[2]
        Tpad = tokens.shape[1]
        x = params["embed"][tokens]
        block_names = [k for k in params if k.startswith("blocks.")]
        stacked = {k.split(".", 1)[1]: params[k] for k in block_names}

        t = jnp.arange(Tpad)
        dest = jnp.where(t < length, page_table[t // ps] * ps + t % ps,
                         t % ps)

        def write(pages_l, kv):
            return (pages_l.reshape(-1, *kv.shape[1:])
                    .at[dest].set(kv).reshape(pages_l.shape))

        def body(x, xs):
            bp, kp, vp = xs
            x, _aux, k, v = self._block(bp, x, use_ring=False, with_kv=True)
            return x, (write(kp, k[0]), write(vp, v[0]))

        x, (k_pages, v_pages) = lax.scan(body, x, (stacked, k_pages, v_pages))
        x = self._rmsnorm(x, params["final_ln_scale"])
        last = lax.dynamic_index_in_dim(x[0], length - 1, axis=0,
                                        keepdims=False)
        logits = jnp.einsum("e,ev->v", last, params["unembed"],
                            preferred_element_type=jnp.float32)
        return k_pages, v_pages, logits

    def decode_step(self, params, k_pages, v_pages, tokens, page_tables,
                    lens, active):
        """One autoregressive step for a batch of decode slots.

        tokens: [S] int32, the token each slot is appending; page_tables:
        [S, M] int32; lens: [S] int32, sequence length BEFORE this token
        (the token is written at position ``lens`` and attends positions
        0..lens); active: [S] bool, writes from inactive slots go to the
        garbage page.  Returns (k_pages, v_pages, logits [S, V] f32) —
        logits for the NEXT token of each slot.  All shapes are static per
        slot-count bucket, so join/leave churn never recompiles.
        """
        cfg = self.cfg
        if cfg.use_moe:
            raise NotImplementedError("paged decode does not support MoE yet")
        H, D = cfg.n_heads, cfg.head_dim
        S = tokens.shape[0]
        ps = k_pages.shape[2]
        x = params["embed"][tokens][:, None, :]            # [S, 1, E]
        block_names = [k for k in params if k.startswith("blocks.")]
        stacked = {k.split(".", 1)[1]: params[k] for k in block_names}

        cur_page = jnp.take_along_axis(page_tables, (lens // ps)[:, None],
                                       axis=1)[:, 0]
        dest = jnp.where(active, cur_page, 0) * ps + lens % ps  # [S]
        span = page_tables.shape[1] * ps
        attn_mask = jnp.arange(span)[None, :] <= lens[:, None]  # [S, span]

        def body(x, xs):
            bp, kp, vp = xs
            h = self._rmsnorm(x, bp["ln1_scale"])
            qkv = jnp.einsum("ste,ef->stf", h, bp["wqkv"],
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(S, H, D)
            kp = kp.reshape(-1, H, D).at[dest].set(
                k.reshape(S, H, D)).reshape(kp.shape)
            vp = vp.reshape(-1, H, D).at[dest].set(
                v.reshape(S, H, D)).reshape(vp.shape)
            kg = kp[page_tables].reshape(S, span, H, D)
            vg = vp[page_tables].reshape(S, span, H, D)
            s = jnp.einsum("shd,skhd->shk", q, kg,
                           preferred_element_type=jnp.float32) / math.sqrt(D)
            s = jnp.where(attn_mask[:, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
            attn = jnp.einsum("shk,skhd->shd", p, vg,
                              preferred_element_type=jnp.float32
                              ).astype(x.dtype)
            o = jnp.einsum("stf,fe->ste", attn.reshape(S, 1, H * D),
                           bp["wo"], preferred_element_type=jnp.float32
                           ).astype(x.dtype)
            x = x + o
            h = self._rmsnorm(x, bp["ln2_scale"])
            up = jnp.einsum("ste,ef->stf", h, bp["w_up"],
                            preferred_element_type=jnp.float32)
            ff = jnp.einsum("stf,fe->ste", jax.nn.gelu(up).astype(x.dtype),
                            bp["w_down"], preferred_element_type=jnp.float32
                            ).astype(x.dtype)
            x = x + ff
            return x, (kp, vp)

        x, (k_pages, v_pages) = lax.scan(body, x, (stacked, k_pages, v_pages))
        x = self._rmsnorm(x, params["final_ln_scale"])
        logits = jnp.einsum("se,ev->sv", x[:, 0], params["unembed"],
                            preferred_element_type=jnp.float32)
        return k_pages, v_pages, logits

    def apply(self, params, tokens):
        """tokens [B, T] int32 -> logits [B, T, V] (f32)."""
        cfg = self.cfg
        x = params["embed"][tokens]
        x = constraint(x, "dp", "sp", None)

        block_names = [k for k in params if k.startswith("blocks.")]
        stacked = {k.split(".", 1)[1]: params[k] for k in block_names}
        # ring attention contains shard_map, which composes under scan/jit
        from ..parallel.mesh import current_mesh
        mesh = current_mesh()
        use_ring = mesh is not None and mesh.size("sp") > 1

        def body(carry, bp):
            x, aux = carry
            x, a = self._block(bp, x, use_ring)
            return (x, aux + a), None

        body_fn = jax.checkpoint(body) if cfg.remat else body
        (x, aux), _ = lax.scan(body_fn, (x, jnp.float32(0.0)), stacked,
                               unroll=bool(cfg.scan_unroll))

        x = self._rmsnorm(x, params["final_ln_scale"])
        logits = jnp.einsum("bte,ev->btv", x, params["unembed"],
                            preferred_element_type=jnp.float32)
        return logits, aux

    def loss(self, params, tokens, targets):
        """Causal LM loss: mean token cross-entropy (+ MoE aux loss)."""
        logits, aux = self.apply(params, tokens)
        from ..ops.pallas.common import select_impl
        xent_fn, xent_impl = select_impl("fused_softmax_xent")
        if xent_impl in ("pallas", "interpret"):
            nll = xent_fn(logits, targets).mean()
        else:
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, targets[..., None],
                                       axis=-1)[..., 0]
            nll = (logz - gold).mean()
        return nll + self.cfg.moe_aux_weight * aux


def make_train_step(model: TransformerLM, lr=1e-2, momentum=0.9, rules=None):
    """Build a jittable SGD-momentum train step:
    (params, velocity, tokens, targets) -> (params, velocity, loss).

    Under an active mesh, jit + GSPMD turn the sharding rules into the full
    collective schedule (grad allreduce over dp, activation collectives for
    tp, ring ppermutes for sp) — the TPU-native replacement for the
    reference's kvstore push/pull training loop (`gluon/trainer.py:302`,
    `kvstore_dist.h`).

    With ``rules`` (a :class:`ShardingRules`), the updated params AND the
    momentum state are constrained to the same per-name shardings — on a
    mesh with an ``fsdp`` axis this is ZeRO-style sharded optimizer state
    (SURVEY §2.4): each device stores only its 1/fsdp slice of every
    parameter and its velocity, and XLA keeps the update math local to the
    shard.
    """
    from ..parallel.sharding import constraint

    def pin(tree):
        if rules is None:
            return tree
        return {k: constraint(v, *rules.spec_for(k))
                for k, v in tree.items()}

    def step(params, velocity, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens, targets)
        grads = pin(grads)
        new_v = pin(jax.tree_util.tree_map(
            lambda v, g: momentum * v + g.astype(v.dtype), velocity, grads))
        new_p = pin(jax.tree_util.tree_map(
            lambda p, v: p - lr * v.astype(p.dtype), params, new_v))
        return new_p, new_v, loss

    return step
