"""Runtime kernel compilation — user-defined accelerator kernels
(reference: ``python/mxnet/rtc.py:42-101`` ``CudaModule``/``CudaKernel``
over NVRTC, ``src/common/rtc.cc``).

TPU-native: the runtime compiler is Pallas/Mosaic instead of NVRTC.
``PallasModule`` accepts Python source text (the analogue of CUDA source
text) or ready callables written against ``jax.experimental.pallas``;
``get_kernel(...).launch(args, grid, out_shape)`` wraps ``pl.pallas_call``
with the same "compile once, launch many" shape.  On hosts without a TPU
the kernel runs in Pallas interpret mode, so the same user code is
testable everywhere (the CPU-oracle strategy of SURVEY §4).
"""
from __future__ import annotations

import numpy as np

from .ndarray import NDArray, _wrap

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]


def _on_tpu():
    import jax

    try:
        return jax.local_devices()[0].platform != "cpu"
    except Exception:
        return False


class PallasModule:
    """Compile user Pallas kernels at runtime (reference CudaModule).

    Parameters
    ----------
    source : str or dict or callable
        Python source text defining one or more kernel functions written
        with ``pl``/``jnp`` primitives (both names are pre-imported into
        the compilation namespace, like NVRTC's implicit headers), or a
        single callable, or a dict name -> callable.
    exports : list of str
        Kernel names exported from source text (reference parity; ignored
        for callables, which export themselves).
    """

    def __init__(self, source, options=(), exports=()):
        self._kernels = {}
        if callable(source):
            self._kernels[source.__name__] = source
        elif isinstance(source, dict):
            self._kernels.update(source)
        elif isinstance(source, str):
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            ns = {"pl": pl, "jnp": jnp, "jax": jax, "np": np}
            exec(compile(source, "<rtc.PallasModule>", "exec"), ns)
            names = exports or [k for k, v in ns.items()
                                if callable(v) and getattr(
                                    v, "__module__", None) is None]
            for name in names:
                if name not in ns:
                    raise ValueError("export %r not found in source" % name)
                self._kernels[name] = ns[name]
        else:
            raise TypeError("source must be str, dict, or callable")

    def get_kernel(self, name, signature=None):
        """Fetch a compiled kernel handle (reference CudaModule.get_kernel;
        ``signature`` is accepted for API parity and unused — shapes/dtypes
        are taken from the launch arguments)."""
        if name not in self._kernels:
            raise ValueError("kernel %r not in module (have: %s)"
                             % (name, sorted(self._kernels)))
        return PallasKernel(name, self._kernels[name])


class PallasKernel:
    """A launchable kernel (reference CudaKernel.launch)."""

    def __init__(self, name, fn):
        self.name = name
        self._fn = fn
        self._compiled = {}

    def launch(self, args, ctx=None, grid=None, out_shape=None,
               out_dtype="float32", **pallas_kwargs):
        """Run the kernel.

        ``args``: list of NDArrays (inputs).  ``grid``: pallas grid tuple
        (the analogue of CUDA grid_dims).  ``out_shape``: output shape
        (defaults to the first input's).  Extra ``pallas_kwargs`` (e.g.
        ``in_specs``/``out_specs``) pass through to ``pl.pallas_call``.
        """
        import jax
        from jax.experimental import pallas as pl

        datas = [a.data if isinstance(a, NDArray) else a for a in args]
        if out_shape is None:
            out_shape = datas[0].shape
            out_dtype = datas[0].dtype
        key = (tuple((d.shape, str(d.dtype)) for d in datas),
               tuple(grid) if grid else None, tuple(out_shape),
               str(out_dtype),
               tuple(sorted((k, repr(v))
                            for k, v in pallas_kwargs.items())))
        call = self._compiled.get(key)
        if call is None:
            kw = dict(pallas_kwargs)
            if grid is not None:
                kw["grid"] = tuple(grid)
            call = jax.jit(pl.pallas_call(
                self._fn,
                out_shape=jax.ShapeDtypeStruct(tuple(out_shape),
                                               np.dtype(out_dtype)),
                interpret=not _on_tpu(), **kw))
            self._compiled[key] = call
        return _wrap(call(*datas))


class CudaModule:
    """Reference-name stub: CUDA runtime compilation has no TPU analogue;
    use :class:`PallasModule` (same get_kernel/launch surface)."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "CudaModule requires NVRTC/CUDA. On TPU builds use "
            "mx.rtc.PallasModule — same get_kernel/launch API over "
            "Pallas kernels.")
