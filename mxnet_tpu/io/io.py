"""Data iterators.

Reference parity: `python/mxnet/io/io.py` — `DataIter` (:178), `NDArrayIter`
(:489, with shuffle/pad/roll-over last-batch handling), `ResizeIter`,
`PrefetchingIter` (double-buffering, the python face of `src/io/
iter_prefetcher.h`), plus host-side reimplementations of the C++ registered
iterators `CSVIter` and `MNISTIter` (`src/io/iter_csv.cc`, `iter_mnist.cc`).
TPU-native: batches are built in numpy on host; device transfer happens when
the consumer touches `.data` (jax moves it async), so prefetch overlaps with
step compute.  Distributed sharding via ``part_index/num_parts`` kwargs
matches the reference's convention for `dist_sync` training.
"""
from __future__ import annotations

import gzip
import os
import struct
import threading

import numpy as np

from .. import base as _base
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "BucketPadIter", "PrefetchingIter", "CSVIter", "MNISTIter"]


class DataDesc:
    """Name + shape (+ dtype/layout) of one data field (reference io.py:84)."""

    def __init__(self, name, shape, dtype=np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    def __eq__(self, other):
        if isinstance(other, DataDesc):
            return (self.name == other.name and self.shape == other.shape)
        if isinstance(other, tuple):
            return (self.name, self.shape) == other
        return NotImplemented

    def __iter__(self):  # tuple-unpacking compat: name, shape = desc
        yield self.name
        yield self.shape

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One minibatch (reference io.py:139)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        lshapes = [getattr(l, "shape", None) for l in (self.label or [])]
        return "DataBatch: data shapes: %s label shapes: %s" % (shapes,
                                                                lshapes)


class DataIter:
    """Base data iterator (reference io.py:178)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError

    # -- mid-epoch resume (elastic.CheckpointManager rides these) ---------
    def state_dict(self):
        """JSON-able snapshot of the iteration position (epoch, cursor,
        shuffle order/rng) for exact mid-epoch resume after preemption.
        Checkpoint it in ``CheckpointManager`` ``extra`` and restore with
        :meth:`load_state_dict`; the resumed iterator replays exactly the
        REMAINING batches, so crash-resume stays bit-identical."""
        raise NotImplementedError(
            "%s does not support mid-epoch resume" % type(self).__name__)

    def load_state_dict(self, state):
        raise NotImplementedError(
            "%s does not support mid-epoch resume" % type(self).__name__)


def _init_data(data, allow_empty, default_name):
    """Normalize input data to a list of (name, numpy array) (io.py:434)."""
    if data is None:
        if not allow_empty:
            raise ValueError("%s cannot be None" % default_name)
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise ValueError("%s cannot be empty" % default_name)
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.ascontiguousarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:489).

    last_batch_handle: 'pad' (wrap around to fill), 'discard', 'roll_over'
    (leftover prepended to next epoch).
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", seed=None):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            if v.shape[0] != self.num_data:
                raise ValueError("all data/label must have the same number "
                                 "of samples")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        # seed: own RandomState so the shuffle order is (a) independent of
        # other np.random consumers and (b) checkpointable — state_dict
        # snapshots it so epochs after a mid-epoch resume shuffle exactly
        # as the uninterrupted run would have.  None keeps the legacy
        # global-np.random behavior (resume then replays the current epoch
        # exactly, but later epochs depend on the ambient global RNG).
        self._seed = seed
        self._rng = np.random.RandomState(seed) if seed is not None else None
        self.idx = np.arange(self.num_data)
        self._leftover = np.array([], dtype=np.int64)  # roll_over carry
        self.cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            (self._rng if self._rng is not None else np.random) \
                .shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and len(self._leftover):
            # the unserved tail of the previous epoch leads this one
            self._order = np.concatenate([self._leftover, self.idx])
        else:
            self._order = self.idx
        self._epoch_size = len(self._order)
        self.cursor = -self.batch_size

    def state_dict(self):
        """Exact-resume snapshot: current epoch order + cursor + roll_over
        carry + (with ``seed=``) the shuffle RNG state."""
        return {
            "cursor": int(self.cursor),
            "order": [int(i) for i in self._order],
            "idx": [int(i) for i in self.idx],
            "leftover": [int(i) for i in self._leftover],
            "batch_size": int(self.batch_size),
            "rng": (_base.encode_rng_state(self._rng)
                    if self._rng is not None else None),
        }

    def load_state_dict(self, state):
        if int(state["batch_size"]) != self.batch_size:
            raise ValueError(
                "iterator resume: batch_size changed (%d -> %d); the "
                "replayed batch boundaries would differ"
                % (state["batch_size"], self.batch_size))
        self.idx = np.asarray(state["idx"], dtype=np.int64)
        self._order = np.asarray(state["order"], dtype=np.int64)
        self._leftover = np.asarray(state["leftover"], dtype=np.int64)
        self._epoch_size = len(self._order)
        self.cursor = int(state["cursor"])
        if state.get("rng") is not None:
            if self._rng is None:
                self._rng = np.random.RandomState()
            self._rng.set_state(_base.decode_rng_state(state["rng"]))

    def iter_next(self):
        self.cursor += self.batch_size
        n = self._epoch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= n
        if self.last_batch_handle == "roll_over":
            if self.cursor + self.batch_size <= n:
                return True
            if self.cursor < n:  # partial tail: carry it to next epoch
                self._leftover = self._order[self.cursor:].copy()
            else:
                self._leftover = np.array([], dtype=np.int64)
            return False
        return self.cursor < n  # pad

    def _take(self, arrays):
        lo = self.cursor
        hi = self.cursor + self.batch_size
        if hi <= self._epoch_size:
            sel = self._order[lo:hi]
        else:  # pad: wrap around
            sel = np.concatenate([self._order[lo:],
                                  self._order[:hi - self._epoch_size]])
        return [nd.array(v[sel]) for _, v in arrays]

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if (self.last_batch_handle == "pad"
                and self.cursor + self.batch_size > self._epoch_size):
            return self.cursor + self.batch_size - self._epoch_size
        return 0


class ResizeIter(DataIter):
    """Resize another iterator to ``size`` batches per epoch (io.py:598)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def state_dict(self):
        return {"cur": int(self.cur),
                "inner": self.data_iter.state_dict()}

    def load_state_dict(self, state):
        self.cur = int(state["cur"])
        self.data_iter.load_state_dict(state["inner"])

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration


class BucketPadIter(DataIter):
    """Pad ragged batches from ``data_iter`` up to a shape bucket so every
    batch a jitted consumer sees has a bucketed leading dim (one compiled
    executable per bucket instead of one per ragged size).

    Pad rows wrap around the batch's real rows — the reference
    ``NDArrayIter`` 'pad' last-batch semantics — and the pad count is
    reported via ``DataBatch.pad`` (added to any padding the inner
    iterator already did) so consumers can mask or slice.

    ``buckets``: None → the MXNET_SHAPE_BUCKETS knob; else a spec
    ('pow2', '8,16,32', or a sequence of sizes)."""

    def __init__(self, data_iter, buckets=None):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        if isinstance(buckets, (list, tuple)):
            buckets = tuple(sorted(int(b) for b in buckets))
        self._buckets = buckets

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.data_iter.reset()

    def state_dict(self):
        # padding is a pure per-batch transform: position state lives
        # entirely in the wrapped iterator
        return self.data_iter.state_dict()

    def load_state_dict(self, state):
        self.data_iter.load_state_dict(state)

    @staticmethod
    def _pad_list(arrays, target):
        from .. import dispatch as _dispatch

        out = []
        for a in arrays:
            if a is None or not getattr(a, "shape", None):
                out.append(a)
            elif isinstance(a, NDArray):
                out.append(NDArray(_dispatch.pad_batch(a.data, target),
                                   ctx=a.context))
            else:
                idx = np.arange(target) % a.shape[0]
                out.append(np.take(a, idx, axis=0))
        return out

    def next(self):
        from .. import dispatch as _dispatch
        from .. import profiler as _prof

        batch = self.data_iter.next()
        data = batch.data if isinstance(batch.data, (list, tuple)) \
            else [batch.data]
        n = data[0].shape[0]
        target = _dispatch.bucket_size(n, self._buckets)
        if target == n:
            return batch
        _prof.dispatch_count("bucket_padded_batches")
        label = (batch.label if isinstance(batch.label, (list, tuple))
                 else ([batch.label] if batch.label is not None else None))
        return DataBatch(
            data=self._pad_list(data, target),
            label=self._pad_list(label, target) if label else batch.label,
            pad=(batch.pad or 0) + (target - n),
            index=batch.index, bucket_key=target,
            provide_data=batch.provide_data,
            provide_label=batch.provide_label)


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators (io.py:659;
    C++ counterpart `src/io/iter_prefetcher.h` double buffer)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.n_iter = len(iters)
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None] * self.n_iter
        self.next_batch = [None] * self.n_iter

        def prefetch(i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch, args=(i,), daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            return False
        self.current_batch = self.next_batch[0] if self.n_iter == 1 else \
            DataBatch(sum([b.data for b in self.next_batch], []),
                      sum([b.label for b in self.next_batch], []),
                      self.next_batch[0].pad, self.next_batch[0].index)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class CSVIter(NDArrayIter):
    """CSV file iterator (reference C++ `src/io/iter_csv.cc`, registered as
    `MXNET_REGISTER_IO_ITER(CSVIter)`).  Host-side numpy loadtxt; supports
    distributed sharding via part_index/num_parts."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=None,
                 batch_size=1, round_batch=True, part_index=0, num_parts=1,
                 **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32,
                          ndmin=2).reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            lshape = tuple(label_shape) if label_shape else (1,)
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2).reshape((-1,) + lshape)
            if label.shape[-1] == 1:
                label = label.reshape(label.shape[:-1])
        if num_parts > 1:
            data = data[part_index::num_parts]
            if label is not None:
                label = label[part_index::num_parts]
        super().__init__(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard", **kwargs)


class LibSVMIter(DataIter):
    """LibSVM text-format iterator (reference C++ `src/io/iter_libsvm.cc`,
    `MXNET_REGISTER_IO_ITER(LibSVMIter)`).

    Each line is ``label idx:val idx:val ...`` (0-based indices).  The
    dataset is held as scipy CSR (memory = nnz, matching the streaming
    reference — the format exists for data too wide to densify); only
    the current batch is densified, served as CSRNDArray (the reference
    yields kCSRStorage blobs), so downstream ``sparse.dot`` rides the
    MXU.  Supports distributed sharding via part_index/num_parts like
    every reference iterator.
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, round_batch=True, part_index=0, num_parts=1,
                 data_name="data", label_name="softmax_label", **kwargs):
        import scipy.sparse as sp  # available via jax deps

        super().__init__(batch_size)
        nfeat = int(data_shape[0] if hasattr(data_shape, "__len__")
                    else data_shape)
        rows, cols, vals, labels = [], [], [], []
        r = 0
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    rows.append(r)
                    cols.append(int(i))
                    vals.append(float(v))
                r += 1
        X = sp.coo_matrix((vals, (rows, cols)), shape=(r, nfeat),
                          dtype=np.float32).tocsr()
        label = np.asarray(labels, np.float32)
        if label_libsvm is not None:
            with open(label_libsvm) as f:
                label = np.asarray([float(l.split()[0])
                                    for l in f if l.split()], np.float32)
        if num_parts > 1:
            X = X[part_index::num_parts]
            label = label[part_index::num_parts]
        self._X, self._y = X, label
        self.num_data = X.shape[0]
        self._nfeat = nfeat
        self._pad = round_batch
        self._data_name, self._label_name = data_name, label_name
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name, (self.batch_size, self._nfeat),
                         np.float32)]

    @property
    def provide_label(self):
        return [DataDesc(self._label_name, (self.batch_size,), np.float32)]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        if self._pad:
            return self.cursor < self.num_data
        return self.cursor + self.batch_size <= self.num_data

    def _sel(self):
        lo, hi = self.cursor, self.cursor + self.batch_size
        if hi <= self.num_data:
            return np.arange(lo, hi)
        return np.concatenate([np.arange(lo, self.num_data),
                               np.arange(hi - self.num_data) % self.num_data])

    def getdata(self):
        from ..ndarray import sparse as _sparse

        # densify ONLY the current batch (batch_size x nfeat)
        batch = np.asarray(self._X[self._sel()].todense(), np.float32)
        return [_sparse.csr_matrix(batch)]

    def getlabel(self):
        return [nd.array(self._y[self._sel()])]

    def getpad(self):
        if self._pad and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class MNISTIter(NDArrayIter):
    """MNIST idx-format iterator (reference C++ `src/io/iter_mnist.cc`).
    Reads the standard (optionally gzipped) idx files."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 seed=0, silent=True, part_index=0, num_parts=1,
                 input_shape=None, **kwargs):
        img = _read_idx(image)
        lbl = _read_idx(label)
        img = img.astype(np.float32) / 255.0
        if flat:
            img = img.reshape(img.shape[0], -1)
        else:
            img = img.reshape(img.shape[0], 1, img.shape[1], img.shape[2])
            if input_shape:
                img = img.reshape((img.shape[0],) + tuple(input_shape))
        if num_parts > 1:
            img = img[part_index::num_parts]
            lbl = lbl[part_index::num_parts]
        if shuffle:
            rs = np.random.RandomState(seed)
            order = rs.permutation(img.shape[0])
            img, lbl = img[order], lbl[order]
        super().__init__(img, lbl.astype(np.float32), batch_size=batch_size,
                         shuffle=False, **kwargs)


def _read_idx(path):
    """Parse an MNIST idx file (magic: 2049 labels / 2051 images)."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">i", f.read(4))[0]
        if magic == 2049:
            (n,) = struct.unpack(">i", f.read(4))
            return np.frombuffer(f.read(n), dtype=np.uint8)
        if magic == 2051:
            n, r, c = struct.unpack(">iii", f.read(12))
            return np.frombuffer(f.read(n * r * c),
                                 dtype=np.uint8).reshape(n, r, c)
        raise ValueError("not an MNIST idx file: %s (magic %d)"
                         % (path, magic))
