"""ImageRecordIter: threaded decode+augment pipeline over RecordIO.

Reference parity: ``src/io/iter_image_recordio_2.cc`` (ImageRecordIter —
OpenMP-parallel JPEG decode + augment + batch) with the C++-iterator kwarg
surface (``data_shape``, ``rand_crop``, ``rand_mirror``, ``mean_r``...,
``part_index``/``num_parts`` sharding, ``preprocess_threads``).

TPU-native shape: a thread pool decodes/augments HOST-side into pinned
numpy batch buffers; each batch is uploaded to the device ONCE; a
double-buffer prefetch thread (the analogue of ``iter_prefetcher.h``) keeps
the host pipeline ahead of the accelerator.
"""
from __future__ import annotations

import os
import queue as _queue
import random as pyrandom
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .. import recordio
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter", "ImageRecordIter_v1"]


class ImageRecordIter(DataIter):
    """Threaded image-record iterator (reference iter_image_recordio_2.cc).

    Parameters follow the reference C++ iterator: ``path_imgrec`` (+
    optional ``path_imgidx`` for shuffle/sharding), ``data_shape`` (C, H,
    W), ``batch_size``, ``shuffle``, ``rand_crop``, ``rand_mirror``,
    ``mean_r/g/b`` + ``std_r/g/b`` (or ``mean_img``), ``resize`` (short
    edge), ``part_index``/``num_parts``, ``preprocess_threads``,
    ``prefetch_buffer``, ``round_batch``.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, rand_crop=False,
                 rand_mirror=False, resize=0, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                 part_index=0, num_parts=1, preprocess_threads=None,
                 prefetch_buffer=4, label_width=1, round_batch=True,
                 seed=0, dtype="float32", data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        from ..config import config
        from ..image import CreateAugmenter

        if preprocess_threads is None:
            preprocess_threads = config.cpu_worker_nthreads

        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.shuffle = shuffle
        self.dtype = dtype
        self.data_name = data_name
        self.label_name = label_name
        self.round_batch = round_batch
        self._rng = pyrandom.Random(seed)

        if path_imgidx is None:
            guess = os.path.splitext(path_imgrec)[0] + ".idx"
            path_imgidx = guess if os.path.exists(guess) else None
        if path_imgidx:
            self._rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                                   "r")
            keys = list(self._rec.keys)
        else:
            if shuffle or num_parts > 1:
                raise ValueError("shuffle/sharding requires an .idx file "
                                 "(path_imgidx)")
            self._rec = recordio.MXRecordIO(path_imgrec, "r")
            keys = None
        if keys is not None and num_parts > 1:
            assert 0 <= part_index < num_parts
            n = len(keys) // num_parts
            keys = keys[part_index * n:(part_index + 1) * n]
        self._keys = keys

        mean = np.array([mean_r, mean_g, mean_b], dtype=np.float32)
        std = np.array([std_r, std_g, std_b], dtype=np.float32)
        self._aug = CreateAugmenter(
            data_shape, resize=resize, rand_crop=rand_crop,
            rand_mirror=rand_mirror,
            mean=mean if mean.any() else None,
            std=std if (std != 1.0).any() else None, **kwargs)
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self._prefetch_n = max(1, prefetch_buffer)
        self._queue = None
        self._producer = None
        self._stop = threading.Event()
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape, self.dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape, "float32")]

    # -- pipeline -------------------------------------------------------
    def _record_stream(self, stop):
        if self._keys is not None:
            order = list(self._keys)
            if self.shuffle:
                self._rng.shuffle(order)
            for k in order:
                if stop.is_set():
                    return
                yield self._rec.read_idx(k)
        else:
            self._rec.reset()
            while not stop.is_set():
                s = self._rec.read()
                if s is None:
                    return
                yield s

    def _decode_one(self, raw):
        from ..image import imdecode
        header, img = recordio.unpack(raw)
        arr = imdecode(img).asnumpy()
        for aug in self._aug:
            arr = aug(arr)
        arr = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        label = np.asarray(header.label, dtype=np.float32).reshape(-1)
        return arr.transpose(2, 0, 1), label[:self.label_width]

    def _produce(self, stop, q):
        # `stop`/`q` are captured per-producer so a reset() (which swaps
        # self._stop/self._queue) can never be raced by an old thread.
        try:
            futures = []
            head = []  # first batch of raw records, for round_batch wrap
            for raw in self._record_stream(stop):
                if len(head) < self.batch_size:
                    head.append(raw)
                futures.append(self._pool.submit(self._decode_one, raw))
                if len(futures) >= self.batch_size:
                    self._emit(stop, q, futures)
                    futures = []
            if futures and not stop.is_set():
                pad = self.batch_size - len(futures)
                if self.round_batch and head:
                    # wrap the tail batch with records from the epoch start
                    # (reference round_batch); pad still reports how many
                    # samples are fill so metrics can ignore them.
                    for i in range(pad):
                        futures.append(self._pool.submit(
                            self._decode_one, head[i % len(head)]))
                self._emit(stop, q, futures, pad=pad)
        except Exception as e:  # surface in the consumer
            self._q_put(stop, q, e)
            return
        self._q_put(stop, q, None)

    @staticmethod
    def _q_put(stop, q, item):
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except _queue.Full:
                continue

    def _emit(self, stop, q, futures, pad=0):
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        labels = np.zeros((self.batch_size, self.label_width),
                          dtype=np.float32)
        for i, f in enumerate(futures):
            img, lab = f.result()
            data[i], labels[i] = img, lab
        self._q_put(stop, q, (data, labels, pad))

    def reset(self):
        self._stop.set()
        if self._producer is not None:
            # drain so a producer blocked on a full queue can observe stop
            while self._producer.is_alive():
                try:
                    self._queue.get_nowait()
                except _queue.Empty:
                    pass
                self._producer.join(timeout=0.05)
        self._stop = threading.Event()
        self._queue = _queue.Queue(maxsize=self._prefetch_n)
        self._producer = threading.Thread(
            target=self._produce, args=(self._stop, self._queue),
            daemon=True)
        self._producer.start()

    def next(self):
        from .. import ndarray as nd
        item = self._queue.get()
        if item is None:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        data, labels, pad = item
        d = nd.array(data.astype(self.dtype))
        l = nd.array(labels.reshape(-1) if self.label_width == 1
                     else labels)
        return DataBatch([d], [l], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


ImageRecordIter_v1 = ImageRecordIter  # reference alias
