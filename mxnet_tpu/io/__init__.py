"""Data IO — iterators, batch types, RecordIO.

Reference parity: `python/mxnet/io/io.py` (DataIter:178, NDArrayIter:489,
MXDataIter:788 wrapping the C++ iterators in `src/io/`), `python/mxnet/
recordio.py`.  TPU-native design: host-side numpy pipeline with double-buffer
prefetch onto device (the reference's `iter_prefetcher.h`), sharded by
`part_index/num_parts` for data parallelism; RecordIO keeps the reference's
on-disk format so existing `.rec` datasets and `im2rec` tooling carry over.
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter,  # noqa: F401
                 ResizeIter, BucketPadIter, PrefetchingIter, CSVIter,
                 LibSVMIter, MNISTIter)
from . import io  # noqa: F401
from .image_iter import ImageRecordIter, ImageRecordIter_v1  # noqa: F401
from ..recordio import (MXRecordIO, MXIndexedRecordIO, IRHeader,  # noqa: F401
                        pack, unpack, pack_img, unpack_img)
