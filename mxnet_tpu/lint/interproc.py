"""mxlint inter-procedural plane: package-wide call graph + fact
propagation (stdlib-only, like the rest of the analyzer).

``Program`` parses every file once into per-module ``ModuleContext``s,
builds a package-wide function table, and runs a fixpoint that
propagates facts across resolved call edges:

* **blocking** — the function (transitively) reaches a blocking
  primitive: socket I/O, ``time.sleep``, thread join (feeds CC001);
* **io_blocking** — restricted to raw socket-level I/O (feeds CC005);
* **host_sync** — the function performs a device->host sync such as
  ``.asnumpy()`` / ``.item()`` (feeds TS001);
* **callback** — the function settles a Future (``set_result`` /
  ``set_exception``) or fires a user callback ``on_*`` (feeds CC004);
* **unbounded** — the function reaches a wait with no timeout:
  ``x.join()`` / eventish ``x.wait()`` / ``input()`` (feeds CC005);

plus the transitive **acquires** set (lock labels the function may
take), from which the global lock acquisition-order graph is built and
cycles reported (CC003) with one witness path per edge.

Name resolution is deliberately conservative — precision over recall:

* ``self.f(...)`` / ``cls.f(...)`` resolve within the enclosing class
  (one level of same-module base classes included) or not at all;
* a bare name resolves to same-module plain functions, an explicit
  ``from x import f`` binding, or a package-unique def of that name;
* ``obj.attr(...)`` resolves only when ``attr`` is package-unique AND
  intention-revealing (underscore-prefixed or snake_case, never a
  generic container/stream verb) — ``q.get()`` does not resolve to some
  random class's blocking ``get``.

Facts carry human-readable witness chains ("_call -> _send_msg ->
sendall() at async_kv.py:203") so a finding three hops from the
primitive still explains itself.
"""
from __future__ import annotations

import ast
import os

from .rules import (BLOCKING_ATTRS, CALLBACK_PREFIXES, EVENTISH_TOKENS,
                    GENERIC_METHOD_NAMES, HOST_SYNC_METHODS,
                    SETTLE_CALLS, ModuleContext, _lock_exprs,
                    _root_name, _terminal_name)

__all__ = ["Program", "FunctionInfo"]

_FACTS = ("blocking", "io_blocking", "host_sync", "callback", "unbounded")
_MAX_ACQUIRES = 24   # per-function transitive lock-label cap
_MAX_WHY = 220       # witness-chain length cap (chars)


def _where(path, node):
    return "%s:%d" % (os.path.basename(path), node.lineno)


def _clip(why):
    return why if len(why) <= _MAX_WHY else why[:_MAX_WHY] + "..."


class FunctionInfo:
    """Per-function facts: direct from one AST scan, then widened by the
    package fixpoint."""

    __slots__ = ("ctx", "node", "name", "cls", "qualname", "blocking",
                 "io_blocking", "host_sync", "host_sync_depth",
                 "callback", "unbounded", "acquires", "calls",
                 "edges_direct")

    def __init__(self, ctx, node):
        self.ctx = ctx
        self.node = node
        self.name = node.name
        self.cls = ctx.class_of.get(id(node))
        self.qualname = ".".join(
            p for p in (ctx.module_stem, self.cls, node.name) if p)
        self.blocking = None     # witness str, or None
        self.io_blocking = None
        self.host_sync = None
        self.host_sync_depth = None  # hops from the direct sync
        self.callback = None
        self.unbounded = None
        self.acquires = {}       # lock label -> (path, line, why)
        self.calls = []          # (Call node, tuple(held lock labels))
        self.edges_direct = []   # (label_a, label_b, path, line, why)

    def __repr__(self):
        return "FunctionInfo(%s)" % self.qualname


class Program:
    """Whole-package analysis state shared by every module's rules."""

    def __init__(self):
        self.contexts = []       # ModuleContext, in add order
        self.functions = []      # FunctionInfo, in add order
        self.by_node = {}        # id(def node) -> FunctionInfo
        self.by_name = {}        # terminal name -> [FunctionInfo]
        self._resolved = {}      # id(Call node) -> tuple(FunctionInfo)
        self._edges = {}         # (a, b) -> (path, line, why)
        self._finalized = False

    # -- construction -----------------------------------------------------
    def add_module(self, tree, path, lines):
        ctx = ModuleContext(tree, path, lines)
        ctx.program = self
        self.contexts.append(ctx)
        for fn in ctx.functions:
            fi = FunctionInfo(ctx, fn)
            self.functions.append(fi)
            self.by_node[id(fn)] = fi
            self.by_name.setdefault(fi.name, []).append(fi)
            self._scan(fi)
        return ctx

    def _scan(self, fi):
        """One pass over the function body: direct facts, calls with the
        lock labels held at each call site, and direct nested-with lock
        edges."""
        ctx = fi.ctx

        def visit(node, held):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef,
                                      ast.Lambda)):
                    continue  # nested defs get their own FunctionInfo
                new_held = held
                if isinstance(child, ast.With):
                    labels = [self._lock_label(e, fi)
                              for e in _lock_exprs(child)]
                    for lbl in labels:
                        fi.acquires.setdefault(
                            lbl, (ctx.path, child.lineno,
                                  "with %s at %s" % (
                                      lbl, _where(ctx.path, child))))
                        for h in held:
                            if h != lbl:
                                fi.edges_direct.append(
                                    (h, lbl, ctx.path, child.lineno,
                                     "%s takes %s inside %s"
                                     % (fi.qualname, lbl, h)))
                    fresh = tuple(l for l in labels if l not in held)
                    if fresh:
                        new_held = held + fresh
                elif isinstance(child, ast.Call):
                    self._note_call(fi, child, held)
                visit(child, new_held)

        visit(fi.node, ())

    def _note_call(self, fi, call, held):
        ctx = fi.ctx
        name = _terminal_name(call.func)
        fi.calls.append((call, held))
        if name is None:
            return
        at = "%s() at %s" % (name, _where(ctx.path, call))
        if ctx.is_blocking_call(call):
            if fi.blocking is None:
                fi.blocking = at
            if name in BLOCKING_ATTRS and fi.io_blocking is None:
                fi.io_blocking = at
        if fi.callback is None and (
                name in SETTLE_CALLS
                or name.startswith(CALLBACK_PREFIXES)):
            fi.callback = at
        if fi.host_sync is None and isinstance(call.func, ast.Attribute) \
                and name in HOST_SYNC_METHODS:
            fi.host_sync = at
            fi.host_sync_depth = 0
        if fi.unbounded is None and not call.args and not call.keywords:
            if isinstance(call.func, ast.Attribute) and name == "join":
                fi.unbounded = "join() with no timeout at %s" \
                    % _where(ctx.path, call)
            elif isinstance(call.func, ast.Attribute) and name == "wait":
                recv = _terminal_name(call.func.value) or ""
                if set(recv.lower().split("_")) & EVENTISH_TOKENS:
                    fi.unbounded = "%s.wait() with no timeout at %s" \
                        % (recv, _where(ctx.path, call))
            elif isinstance(call.func, ast.Name) and name == "input":
                fi.unbounded = "input() at %s" % _where(ctx.path, call)

    def _lock_label(self, expr, fi):
        """Stable identity for a lock expression.  ``self._lock`` in a
        method of ``C`` in module ``m`` -> ``m.C._lock`` (every instance
        of the class shares ordering discipline); module globals ->
        ``m.name``; function locals -> ``m.fn.name``."""
        ctx = fi.ctx
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                    and fi.cls:
                return "%s.%s.%s" % (ctx.module_stem, fi.cls, expr.attr)
            root = _root_name(expr)
            return "%s.%s" % (root or "?", expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in ctx.module_globals:
                return "%s.%s" % (ctx.module_stem, expr.id)
            return "%s.%s.%s" % (ctx.module_stem, fi.name, expr.id)
        return _terminal_name(expr) or "<lock>"

    # -- resolution -------------------------------------------------------
    def _method_in_class(self, ctx, cls, name, _depth=0):
        node = ctx.class_methods.get(cls, {}).get(name)
        if node is not None:
            return self.by_node.get(id(node))
        if _depth >= 2:
            return None
        # one level of same-module inheritance
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.ClassDef) and n.name == cls:
                for base in n.bases:
                    bname = _terminal_name(base)
                    if bname and bname in ctx.class_methods:
                        got = self._method_in_class(ctx, bname, name,
                                                    _depth + 1)
                        if got is not None:
                            return got
        return None

    def _unique(self, name):
        if name in GENERIC_METHOD_NAMES:
            return []
        cands = self.by_name.get(name, ())
        return list(cands) if len(cands) == 1 else []

    def resolve_callable(self, ctx, caller, expr):
        """Resolve a callee expression to FunctionInfos.  ``caller`` is
        the enclosing def node (or FunctionInfo, or None for module
        level)."""
        if isinstance(caller, ast.AST):
            caller = self.by_node.get(id(caller))
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Name):
            name = expr.id
            if caller is not None and name in ctx.params_of(caller.node):
                return []  # a passed-in callable: unresolvable
            binding = ctx.from_imports.get(name)
            if binding is not None:
                got = self._from_module(binding[0], binding[1])
                if got:
                    return got
            local = [fi for fi in self.by_name.get(name, ())
                     if fi.ctx is ctx and fi.cls is None]
            if local:
                return local
            return self._unique(name)
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and caller is not None \
                        and caller.cls:
                    got = self._method_in_class(ctx, caller.cls, attr)
                    return [got] if got is not None else []
                stem = ctx.mod_aliases.get(base.id)
                if stem is not None:
                    return self._from_module(stem, attr)
            if attr.startswith("_") or ("_" in attr and
                                        attr not in GENERIC_METHOD_NAMES):
                return self._unique(attr)
            return []
        return []

    def _from_module(self, stem, name):
        out = []
        for ctx in self.contexts:
            if ctx.module_stem != stem:
                continue
            for fi in self.by_name.get(name, ()):
                if fi.ctx is ctx and fi.cls is None:
                    out.append(fi)
        return out

    # -- fixpoint ---------------------------------------------------------
    def finalize(self):
        """Resolve every call site once, then widen facts and transitive
        lock-acquire sets to a fixpoint; finally union the global lock
        acquisition-order graph."""
        if self._finalized:
            return
        self._finalized = True
        for fi in self.functions:
            for call, _held in fi.calls:
                self._resolved[id(call)] = tuple(
                    c for c in self.resolve_callable(fi.ctx, fi, call.func)
                    if c is not fi)
        changed, rounds = True, 0
        while changed and rounds < 50:
            changed, rounds = False, rounds + 1
            for fi in self.functions:
                for call, _held in fi.calls:
                    for callee in self._resolved.get(id(call), ()):
                        for fact in _FACTS:
                            if getattr(fi, fact) is not None or \
                                    getattr(callee, fact) is None:
                                continue
                            if fact == "host_sync":
                                # Host-sync facts decay: past 2 hops the
                                # chain is almost always host-side
                                # bookkeeping (cache keys, logging), not
                                # a tracer sync worth flagging.
                                d = callee.host_sync_depth
                                if d is None or d >= 2:
                                    continue
                                fi.host_sync_depth = d + 1
                            setattr(fi, fact, _clip(
                                "%s -> %s" % (callee.qualname,
                                              getattr(callee, fact))))
                            changed = True
                        if len(fi.acquires) < _MAX_ACQUIRES:
                            for lbl, (p, ln, why) in \
                                    callee.acquires.items():
                                if lbl not in fi.acquires:
                                    fi.acquires[lbl] = (p, ln, _clip(
                                        "via %s: %s" % (callee.qualname,
                                                        why)))
                                    changed = True
        for fi in self.functions:
            for (a, b, p, ln, why) in fi.edges_direct:
                self._edges.setdefault((a, b), (p, ln, why))
            for call, held in fi.calls:
                if not held:
                    continue
                for callee in self._resolved.get(id(call), ()):
                    for lbl, (_p, _ln, why) in callee.acquires.items():
                        for h in held:
                            if h != lbl:
                                self._edges.setdefault(
                                    (h, lbl),
                                    (fi.ctx.path, call.lineno, _clip(
                                        "%s calls %s under %s; %s"
                                        % (fi.qualname, callee.qualname,
                                           h, why))))

    # -- rule queries -----------------------------------------------------
    def _fact_of_call(self, ctx, caller, call, fact):
        if not self._finalized:
            self.finalize()
        callees = self._resolved.get(id(call))
        if callees is None:  # call site outside any scanned function
            callees = tuple(self.resolve_callable(ctx, caller, call.func))
        for callee in callees:
            why = getattr(callee, fact)
            if why is not None:
                return callee, why
        return None, None

    def blocking_callee(self, ctx, caller, call):
        """Witness chain if the resolved callee transitively blocks."""
        callee, why = self._fact_of_call(ctx, caller, call, "blocking")
        if callee is None:
            return None
        return _clip("%s -> %s" % (callee.qualname, why)
                     if not why.startswith(callee.qualname) else why)

    def host_sync_callee(self, ctx, caller, call):
        """Witness chain if the resolved callee transitively performs a
        device->host sync (traced callees excluded — they are flagged at
        the source)."""
        callee, why = self._fact_of_call(ctx, caller, call, "host_sync")
        if callee is None or callee.node in callee.ctx.traced:
            return None
        return _clip("%s: %s" % (callee.qualname, why))

    def callback_callee(self, ctx, caller, call):
        """Witness chain if the resolved callee settles a future or
        fires a user callback."""
        callee, why = self._fact_of_call(ctx, caller, call, "callback")
        if callee is None:
            return None
        return _clip("%s: %s" % (callee.qualname, why))

    # -- lock-order cycles (CC003) ----------------------------------------
    def lock_cycles(self):
        """Enumerate acquisition-order cycles, one per distinct node
        set, as lists of ``(a, b, path, line, why)`` edges."""
        if not self._finalized:
            self.finalize()
        adj = {}
        for (a, b) in self._edges:
            adj.setdefault(a, []).append(b)
        seen = set()
        cycles = []
        for (a, b) in sorted(self._edges):
            path_back = self._bfs(b, a, adj)
            if path_back is None:
                continue
            nodes = [a] + path_back  # [a, b, ..., a]
            key = frozenset(nodes)
            if key in seen:
                continue
            seen.add(key)
            edges = []
            for x, y in zip(nodes, nodes[1:]):
                wit = self._edges.get((x, y))
                if wit is None:
                    continue
                edges.append((x, y, wit[0], wit[1], wit[2]))
            if edges:
                cycles.append(edges)
        return cycles

    def _bfs(self, start, goal, adj):
        """Shortest path start -> ... -> goal, or None."""
        if start == goal:
            return [start]
        frontier = [start]
        came = {start: None}
        while frontier:
            nxt = []
            for n in frontier:
                for m in adj.get(n, ()):
                    if m in came:
                        continue
                    came[m] = n
                    if m == goal:
                        out = [m]
                        while came[out[-1]] is not None:
                            out.append(came[out[-1]])
                        return list(reversed(out))
                    nxt.append(m)
            frontier = nxt
        return None
