"""mxlint core: rule registry, findings, suppressions, reporters.

Deliberately stdlib-only.  The analyzer parses files with ``ast`` and
routes the tree through every registered rule; rules are small functions
``check(ctx) -> iterable[Finding]`` registered via :func:`register_rule`
so projects (and tests) can extend the rule set without touching the
driver.  Suppression directives are read from the raw source lines
(``# mxlint: disable=RULE``), pylint-style: a trailing comment silences
its own line, a standalone directive line silences the next line, and
``# mxlint: disable-block=RULE`` on (or immediately above) a compound
statement silences the whole statement body — ONE audit point for a
deliberately-held critical section instead of a comment per line.

Every lint entry point builds an :class:`~.interproc.Program` over the
linted set — a single file gets intra-module transitivity, a package run
gets the full cross-module call graph — so rules can consult
``ctx.program`` unconditionally.  Rules registered with
:func:`register_program_rule` run once per program (not per module) and
yield findings anchored to witness files.
"""
from __future__ import annotations

import ast
import json
import os
import re

__all__ = ["Severity", "Finding", "Rule", "RULES", "LintError",
           "register_rule", "register_program_rule", "lint_source",
           "lint_file", "lint_paths", "format_text", "format_json"]


class Severity:
    """Finding severities.  ``error`` fails the run (exit 1); ``warning``
    is reported but only fails under ``--strict``."""

    ERROR = "error"
    WARNING = "warning"

    ORDER = (ERROR, WARNING)


class LintError(RuntimeError):
    """Internal analyzer failure (not a finding)."""


class Finding:
    """One diagnostic: (rule, severity, path, line, col, message)."""

    __slots__ = ("rule", "severity", "path", "line", "col", "message")

    def __init__(self, rule, severity, path, line, col, message):
        self.rule = rule
        self.severity = severity
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message

    def as_dict(self):
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message}

    def format(self):
        return "%s:%d:%d: %s [%s] %s" % (self.path, self.line, self.col,
                                         self.rule, self.severity,
                                         self.message)

    def __repr__(self):
        return "Finding(%s)" % self.format()


class Rule:
    """A registered rule: id, default severity, one-line summary, scope,
    and the checker.  Module-scope checkers take a ``ModuleContext`` and
    yield ``(node_or_line, col, msg)``; program-scope checkers take a
    ``Program`` and yield ``(path, node_or_line, col, msg)``."""

    __slots__ = ("id", "severity", "summary", "doc", "checker", "scope")

    def __init__(self, id, severity, summary, checker, doc=None,
                 scope="module"):
        self.id = id
        self.severity = severity
        self.summary = summary
        self.checker = checker
        self.doc = doc or (checker.__doc__ or "").strip()
        self.scope = scope


#: rule id -> Rule.  Populated by :func:`register_rule` (rules.py imports
#: at package import register the builtin set).
RULES: dict = {}


def _register(rule_id, severity, summary, scope):
    assert re.fullmatch(r"[A-Z]{2}\d{3}", rule_id), rule_id

    def deco(fn):
        if rule_id in RULES:
            raise LintError("duplicate rule id %s" % rule_id)
        RULES[rule_id] = Rule(rule_id, severity, summary, fn, scope=scope)
        return fn

    return deco


def register_rule(rule_id, severity, summary):
    """Decorator: register ``fn(ctx)`` as module-scope rule ``rule_id``.

    The checker receives a :class:`mxnet_tpu.lint.rules.ModuleContext`
    and yields ``(lineno, col, message)`` triples (or ast nodes in place
    of ``lineno``, from which position is taken)."""
    return _register(rule_id, severity, summary, "module")


def register_program_rule(rule_id, severity, summary):
    """Decorator: register ``fn(program)`` as a program-scope rule that
    runs ONCE per lint invocation over the package-wide
    :class:`~.interproc.Program`.  The checker yields
    ``(path, lineno_or_node, col, message)`` — findings are anchored to
    the named witness file and honor that file's suppressions."""
    return _register(rule_id, severity, summary, "program")


# -- suppressions -----------------------------------------------------------
_DIRECTIVE = re.compile(
    r"#\s*mxlint:\s*(?P<verb>disable-block|disable|skip-file)\s*"
    r"(?:=\s*(?P<rules>[A-Za-z0-9_,\s]+?))?\s*(?:—|--|\.|$)")


def _parse_suppressions(lines):
    """``(skip_file, per_line, block_directives)`` from raw source
    lines.  ``per_line`` maps lineno -> set(rule ids) | {'all'}; a
    directive with code before the ``#`` applies to its own line, a
    standalone comment line applies to the following line too.
    ``block_directives`` is ``[(lineno, rules, standalone)]`` for
    ``disable-block`` directives, resolved to statement extents once the
    AST is available."""
    skip_file = False
    per_line = {}
    blocks = []
    for i, raw in enumerate(lines, start=1):
        m = _DIRECTIVE.search(raw)
        if not m:
            continue
        verb = m.group("verb")
        if verb == "skip-file":
            skip_file = True
            continue
        rules = {r.strip().upper() for r in
                 (m.group("rules") or "all").split(",") if r.strip()}
        if not rules:
            rules = {"ALL"}
        rules = {"all" if r == "ALL" else r for r in rules}
        standalone = not raw.split("#", 1)[0].strip()
        if verb == "disable-block":
            blocks.append((i, rules, standalone))
            continue
        targets = [i]
        if standalone:
            targets.append(i + 1)  # standalone directive: next line too
        for t in targets:
            per_line.setdefault(t, set()).update(rules)
    return skip_file, per_line, blocks


def _block_ranges(tree, blocks):
    """Resolve ``disable-block`` directives to ``(start, end, rules)``
    line ranges: the widest statement starting on the directive line
    (trailing form) or the next line (standalone form)."""
    if not blocks:
        return []
    stmts = [n for n in ast.walk(tree)
             if isinstance(n, ast.stmt) and getattr(n, "end_lineno", None)]
    ranges = []
    for (line, rules, standalone) in blocks:
        starts = {line, line + 1} if standalone else {line}
        cands = [n for n in stmts if n.lineno in starts]
        if not cands:
            continue
        best = max(cands, key=lambda n: n.end_lineno - n.lineno)
        ranges.append((best.lineno, best.end_lineno, rules))
    return ranges


def _suppressed(finding, per_line, ranges=()):
    got = per_line.get(finding.line)
    if got and ("all" in got or finding.rule in got):
        return True
    for (start, end, rules) in ranges:
        if start <= finding.line <= end and \
                ("all" in rules or finding.rule in rules):
            return True
    return False


# -- driver -----------------------------------------------------------------
class _Entry:
    """One file staged for linting: raw text, suppression state, and the
    parse result (tree, or a synthetic PARSE finding)."""

    __slots__ = ("path", "source", "lines", "skip", "per_line", "ranges",
                 "tree", "ctx", "parse_finding")

    def __init__(self, source, path):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.skip, self.per_line, blocks = _parse_suppressions(self.lines)
        self.tree = None
        self.ctx = None
        self.parse_finding = None
        self.ranges = ()
        if self.skip:
            return
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_finding = Finding(
                "PARSE", Severity.ERROR, path, e.lineno or 1,
                (e.offset or 1) - 1, "syntax error: %s" % e.msg)
            return
        self.ranges = _block_ranges(self.tree, blocks)


def _rule_enabled(rule, select, disable):
    if select and rule.id not in select:
        return False
    if disable and rule.id in disable:
        return False
    return True


def _lint_entries(entries, select=None, disable=None):
    """Shared driver: build one Program over every parseable entry, run
    module rules per file and program rules once, honor suppressions."""
    from .interproc import Program

    program = Program()
    live = []
    by_path = {}
    out = {}  # path -> [Finding]
    for e in entries:
        out[e.path] = []
        if e.skip:
            continue
        if e.parse_finding is not None:
            out[e.path].append(e.parse_finding)
            continue
        e.ctx = program.add_module(e.tree, e.path, e.lines)
        live.append(e)
        by_path[e.path] = e
    program.finalize()

    module_rules = [r for r in RULES.values() if r.scope == "module"
                    and _rule_enabled(r, select, disable)]
    program_rules = [r for r in RULES.values() if r.scope == "program"
                     and _rule_enabled(r, select, disable)]
    for e in live:
        for rule in module_rules:
            for hit in rule.checker(e.ctx):
                node_or_line, col, message = hit
                if isinstance(node_or_line, ast.AST):
                    line = node_or_line.lineno
                    col = node_or_line.col_offset if col is None else col
                else:
                    line = node_or_line
                f = Finding(rule.id, rule.severity, e.path, line,
                            col or 0, message)
                if not _suppressed(f, e.per_line, e.ranges):
                    out[e.path].append(f)
    for rule in program_rules:
        for hit in rule.checker(program):
            path, node_or_line, col, message = hit
            if isinstance(node_or_line, ast.AST):
                line = node_or_line.lineno
                col = node_or_line.col_offset if col is None else col
            else:
                line = node_or_line
            f = Finding(rule.id, rule.severity, path, line, col or 0,
                        message)
            e = by_path.get(path)
            if e is None or not _suppressed(f, e.per_line, e.ranges):
                out.setdefault(path, []).append(f)

    findings = []
    for path in out:
        findings.extend(sorted(out[path],
                               key=lambda f: (f.line, f.col, f.rule)))
    return findings


def lint_source(source, path="<string>", select=None, disable=None):
    """Lint one source string; returns a list of :class:`Finding`.

    ``select``/``disable``: iterables of rule ids restricting which rules
    run.  Suppression comments are honored.  A syntax error yields a
    single synthetic ``PARSE``-rule error finding rather than raising, so
    one broken file cannot take down a whole-tree run.  A one-module
    Program backs ``ctx.program``, so inter-procedural facts resolve
    within the file."""
    return _lint_entries([_Entry(source, path)], select=select,
                         disable=disable)


def lint_file(path, select=None, disable=None):
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), path=path, select=select,
                           disable=disable)


def iter_python_files(paths):
    """Expand files/directories into .py files (sorted, deduped;
    __pycache__ and hidden directories skipped)."""
    seen = []
    for p in paths:
        if os.path.isfile(p):
            if p not in seen:
                seen.append(p)
            continue
        if not os.path.isdir(p):
            raise LintError("no such file or directory: %s" % p)
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__" and not d.startswith("."))
            for fn in sorted(files):
                if fn.endswith(".py"):
                    full = os.path.join(root, fn)
                    if full not in seen:
                        seen.append(full)
    return seen


def lint_paths(paths, select=None, disable=None):
    """Lint files/trees as ONE program (cross-module facts flow between
    every file in the set); returns (findings, n_files)."""
    files = iter_python_files(paths)
    entries = []
    for path in files:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            entries.append(_Entry(f.read(), path))
    return _lint_entries(entries, select=select, disable=disable), \
        len(files)


# -- reporters --------------------------------------------------------------
def format_text(findings, n_files=None):
    out = [f.format() for f in findings]
    counts = _counts(findings, n_files)
    tail = "%d error(s), %d warning(s)" % (counts["error"],
                                           counts["warning"])
    if n_files is not None:
        tail += " in %d file(s)" % n_files
    out.append(tail)
    return "\n".join(out)


def _counts(findings, n_files=None):
    counts = {"error": 0, "warning": 0}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    if n_files is not None:
        counts["files"] = n_files
    return counts


def format_json(findings, n_files=None):
    """Stable machine-readable report (schema asserted by
    tests/test_lint.py; bump ``version`` on breaking changes)."""
    payload = {
        "version": 1,
        "tool": "mxlint",
        "counts": _counts(findings, n_files),
        "findings": [f.as_dict() for f in findings],
    }
    return json.dumps(payload, indent=2, sort_keys=True)
