"""mxlint core: rule registry, findings, suppressions, reporters.

Deliberately stdlib-only.  The analyzer parses files with ``ast`` and
routes the tree through every registered rule; rules are small functions
``check(ctx) -> iterable[Finding]`` registered via :func:`register_rule`
so projects (and tests) can extend the rule set without touching the
driver.  Suppression directives are read from the raw source lines
(``# mxlint: disable=RULE``), pylint-style: a trailing comment silences
its own line, a standalone directive line silences the next line.
"""
from __future__ import annotations

import ast
import json
import os
import re

__all__ = ["Severity", "Finding", "Rule", "RULES", "LintError",
           "register_rule", "lint_source", "lint_file", "lint_paths",
           "format_text", "format_json"]


class Severity:
    """Finding severities.  ``error`` fails the run (exit 1); ``warning``
    is reported but only fails under ``--strict``."""

    ERROR = "error"
    WARNING = "warning"

    ORDER = (ERROR, WARNING)


class LintError(RuntimeError):
    """Internal analyzer failure (not a finding)."""


class Finding:
    """One diagnostic: (rule, severity, path, line, col, message)."""

    __slots__ = ("rule", "severity", "path", "line", "col", "message")

    def __init__(self, rule, severity, path, line, col, message):
        self.rule = rule
        self.severity = severity
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message

    def as_dict(self):
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message}

    def format(self):
        return "%s:%d:%d: %s [%s] %s" % (self.path, self.line, self.col,
                                         self.rule, self.severity,
                                         self.message)

    def __repr__(self):
        return "Finding(%s)" % self.format()


class Rule:
    """A registered rule: id, default severity, one-line summary, and the
    checker ``fn(ModuleContext) -> iterable[(node_or_line, col, msg)]``
    (checkers yield positions; the driver stamps rule/severity/path)."""

    __slots__ = ("id", "severity", "summary", "doc", "checker")

    def __init__(self, id, severity, summary, checker, doc=None):
        self.id = id
        self.severity = severity
        self.summary = summary
        self.checker = checker
        self.doc = doc or (checker.__doc__ or "").strip()


#: rule id -> Rule.  Populated by :func:`register_rule` (rules.py imports
#: at package import register the builtin set).
RULES: dict = {}


def register_rule(rule_id, severity, summary):
    """Decorator: register ``fn(ctx)`` as rule ``rule_id``.

    The checker receives a :class:`mxnet_tpu.lint.rules.ModuleContext`
    and yields ``(lineno, col, message)`` triples (or ast nodes in place
    of ``lineno``, from which position is taken)."""
    assert re.fullmatch(r"[A-Z]{2}\d{3}", rule_id), rule_id

    def deco(fn):
        if rule_id in RULES:
            raise LintError("duplicate rule id %s" % rule_id)
        RULES[rule_id] = Rule(rule_id, severity, summary, fn)
        return fn

    return deco


# -- suppressions -----------------------------------------------------------
_DIRECTIVE = re.compile(
    r"#\s*mxlint:\s*(?P<verb>disable|skip-file)\s*"
    r"(?:=\s*(?P<rules>[A-Za-z0-9_,\s]+?))?\s*(?:—|--|\.|$)")


def _parse_suppressions(lines):
    """(skip_file, {lineno: set(rule_ids) | {'all'}}) from raw source
    lines.  A directive with code before the ``#`` applies to its own
    line; a standalone comment line applies to the following line too."""
    skip_file = False
    per_line = {}
    for i, raw in enumerate(lines, start=1):
        m = _DIRECTIVE.search(raw)
        if not m:
            continue
        if m.group("verb") == "skip-file":
            skip_file = True
            continue
        rules = {r.strip().upper() for r in
                 (m.group("rules") or "all").split(",") if r.strip()}
        if not rules:
            rules = {"ALL"}
        rules = {"all" if r == "ALL" else r for r in rules}
        targets = [i]
        if not raw.split("#", 1)[0].strip():
            targets.append(i + 1)  # standalone directive: next line too
        for t in targets:
            per_line.setdefault(t, set()).update(rules)
    return skip_file, per_line


def _suppressed(finding, per_line):
    got = per_line.get(finding.line)
    return bool(got) and ("all" in got or finding.rule in got)


# -- driver -----------------------------------------------------------------
def lint_source(source, path="<string>", select=None, disable=None):
    """Lint one source string; returns a list of :class:`Finding`.

    ``select``/``disable``: iterables of rule ids restricting which rules
    run.  Suppression comments are honored.  A syntax error yields a
    single synthetic ``PARSE``-rule error finding rather than raising, so
    one broken file cannot take down a whole-tree run."""
    from .rules import ModuleContext

    lines = source.splitlines()
    skip_file, per_line = _parse_suppressions(lines)
    if skip_file:
        return []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("PARSE", Severity.ERROR, path, e.lineno or 1,
                        (e.offset or 1) - 1, "syntax error: %s" % e.msg)]
    ctx = ModuleContext(tree, path, lines)
    findings = []
    for rule in RULES.values():
        if select and rule.id not in select:
            continue
        if disable and rule.id in disable:
            continue
        for hit in rule.checker(ctx):
            node_or_line, col, message = hit
            if isinstance(node_or_line, ast.AST):
                line = node_or_line.lineno
                col = node_or_line.col_offset if col is None else col
            else:
                line = node_or_line
            f = Finding(rule.id, rule.severity, path, line, col or 0,
                        message)
            if not _suppressed(f, per_line):
                findings.append(f)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_file(path, select=None, disable=None):
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), path=path, select=select,
                           disable=disable)


def iter_python_files(paths):
    """Expand files/directories into .py files (sorted, deduped;
    __pycache__ and hidden directories skipped)."""
    seen = []
    for p in paths:
        if os.path.isfile(p):
            if p not in seen:
                seen.append(p)
            continue
        if not os.path.isdir(p):
            raise LintError("no such file or directory: %s" % p)
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__" and not d.startswith("."))
            for fn in sorted(files):
                if fn.endswith(".py"):
                    full = os.path.join(root, fn)
                    if full not in seen:
                        seen.append(full)
    return seen


def lint_paths(paths, select=None, disable=None):
    """Lint files/trees; returns (findings, n_files)."""
    findings = []
    files = iter_python_files(paths)
    for path in files:
        findings.extend(lint_file(path, select=select, disable=disable))
    return findings, len(files)


# -- reporters --------------------------------------------------------------
def format_text(findings, n_files=None):
    out = [f.format() for f in findings]
    counts = _counts(findings, n_files)
    tail = "%d error(s), %d warning(s)" % (counts["error"],
                                           counts["warning"])
    if n_files is not None:
        tail += " in %d file(s)" % n_files
    out.append(tail)
    return "\n".join(out)


def _counts(findings, n_files=None):
    counts = {"error": 0, "warning": 0}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    if n_files is not None:
        counts["files"] = n_files
    return counts


def format_json(findings, n_files=None):
    """Stable machine-readable report (schema asserted by
    tests/test_lint.py; bump ``version`` on breaking changes)."""
    payload = {
        "version": 1,
        "tool": "mxlint",
        "counts": _counts(findings, n_files),
        "findings": [f.as_dict() for f in findings],
    }
    return json.dumps(payload, indent=2, sort_keys=True)
