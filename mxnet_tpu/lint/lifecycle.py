"""mxlint v3: resource-lifecycle and exactly-once-outcome dataflow rules.

======  ==========================================================
RL001   resource acquired but not released on some exit path
RL002   resource released twice on one path (double-release)
RL003   future created/admitted but not settled on every path out
        of the owning scope
RL004   settle reachable twice on one path (double-settle)
======  ==========================================================

The engine is a path-sensitive, intra-procedural symbolic walk over
each function body, riding the package-wide :class:`~.interproc.Program`
for cross-helper resolution.  It is driven by a declarative *pair
registry*: a subsystem declares its acquire/release (or create/settle)
contract with :func:`register_pair` and the analyzer enforces it --
new subsystems register their contracts instead of editing the
analyzer.

Precision contract
------------------
The serving/generation/gateway/fleet modules are linted with NO
suppressions (the CI lanes grep for and reject ``mxlint: disable``
there), so every transition prefers a missed finding over a false
positive:

* an acquire may return ``None`` (``PageAllocator.alloc`` is
  all-or-nothing): handles are *maybe-held*, and an ``if h is None``
  test refines the branch states instead of forking a false leak;
* a handle that escapes the scope -- returned, yielded, raised, stored
  into an attribute/subscript/container, captured by a nested def, or
  passed to a call the Program cannot uniquely resolve -- transfers
  ownership and ends tracking;
* a resolved helper call applies the callee's computed per-parameter
  release/escape facts (one helper deep and beyond, to a fixpoint);
  only a callee that provably neither releases nor escapes the handle
  leaves it held in the caller;
* only *explicit* ``raise`` statements count as exceptional exits
  (every call can in principle raise -- modelling that would flag all
  non-``finally`` code);
* a function whose path fan-out exceeds the budget is skipped outright.
"""

from __future__ import annotations

import ast

from .core import Severity, register_program_rule
from .rules import _dotted, _terminal_name

__all__ = ["LifecyclePair", "register_pair", "unregister_pair", "pairs"]

_MAX_PATHS = 96          # live path states per statement list
_SELF = ("self", "cls")

# handle states (per path)
_HELD, _RELEASED, _ESCAPED = "held", "released", "escaped"


class LifecyclePair:
    """One declared acquire/release (or create/settle) contract.

    ``kind``:
      * ``"value"``    -- the acquire call *returns* the handle
        (``pages = allocator.alloc(n)``); releases take the handle as
        an argument (``allocator.free(pages)``) or as the receiver root
        (``fut._reject(err)``).
      * ``"receiver"`` -- acquire and release are methods on the same
        receiver (``b.acquire_probe()`` / ``b.release_probe()``); the
        resource identity is the dotted receiver.  Calls whose receiver
        is bare ``self``/``cls`` are the contract's *implementation*
        (the resource class mutating its own slot) and are exempt.

    ``family``: ``"resource"`` findings report as RL001/RL002,
    ``"outcome"`` (exactly-once settle) as RL003/RL004.

    ``acquire_recv`` / ``release_recv``: optional receiver-name token
    sets -- the call only matches when the dotted receiver shares a
    token (``self._free_slices`` -> ``{"self", "free", "slices"}``).
    Required for generic method names like ``popleft``/``append``.

    ``attr_recv_only``: acquire must be an attribute call with a dotted
    (depth >= 2) receiver -- ``self._pending.popleft()`` matches, a
    bare local ``pending.popleft()`` does not.

    ``ctors``: constructor names whose call *creates* the handle
    (``StreamingFuture(...)``), for outcome pairs.
    """

    __slots__ = ("name", "family", "kind", "acquire", "release",
                 "acquire_recv", "release_recv", "attr_recv_only",
                 "ctors", "describe", "advice")

    def __init__(self, name, family, kind, acquire=(), release=(),
                 acquire_recv=(), release_recv=(), attr_recv_only=False,
                 ctors=(), describe="", advice=""):
        assert family in ("resource", "outcome"), family
        assert kind in ("value", "receiver"), kind
        self.name = name
        self.family = family
        self.kind = kind
        self.acquire = frozenset(acquire)
        self.release = frozenset(release)
        self.acquire_recv = frozenset(acquire_recv)
        self.release_recv = frozenset(release_recv)
        self.attr_recv_only = bool(attr_recv_only)
        self.ctors = frozenset(ctors)
        self.describe = describe or name
        self.advice = advice or ("release it (%s) on every exit path or "
                                 "hand ownership off explicitly"
                                 % "/".join(sorted(self.release)))


_PAIRS: list = []


def register_pair(pair):
    """Register a lifecycle contract (idempotent by ``pair.name``)."""
    unregister_pair(pair.name)
    _PAIRS.append(pair)
    return pair


def unregister_pair(name):
    _PAIRS[:] = [p for p in _PAIRS if p.name != name]


def pairs():
    return tuple(_PAIRS)


# -- the built-in contracts (the serving arc's hand-enforced invariants) ----
register_pair(LifecyclePair(
    "kv-pages", "resource", "value",
    acquire=("alloc",), release=("free",),
    describe="KV cache pages (PageAllocator.alloc/free)"))
register_pair(LifecyclePair(
    "probe-slot", "resource", "receiver",
    acquire=("acquire_probe",),
    release=("release_probe", "record_success", "record_failure"),
    describe="half-open breaker probe slot "
             "(CircuitBreaker.acquire_probe/release_probe)",
    advice="release it (release_probe, or record_success/record_failure "
           "with an outcome) on every exit path, or the slot stays taken "
           "and the replica never rejoins rotation"))
register_pair(LifecyclePair(
    "mesh-slice", "resource", "value",
    acquire=("popleft", "pop"), acquire_recv=("slices", "slice"),
    release=("append", "appendleft"), release_recv=("slices", "slice"),
    attr_recv_only=True,
    describe="mesh slice pool entry (free-slice popleft/append)"))
register_pair(LifecyclePair(
    "journal-entry", "resource", "value",
    acquire=("add", "admit"), acquire_recv=("journal",),
    release=("evict", "remove", "pop", "discard"),
    release_recv=("journal",), attr_recv_only=True,
    describe="stream journal entry (journal add/evict)"))
register_pair(LifecyclePair(
    "typed-outcome", "outcome", "value",
    acquire=("popleft",), acquire_recv=("pending",), attr_recv_only=True,
    ctors=("ServingFuture", "StreamingFuture"),
    release=("_resolve", "_reject", "_settle",
             "set_result", "set_exception"),
    describe="admitted request future (exactly-once typed outcome)",
    advice="settle it (_resolve/_reject) on every path out of the owning "
           "scope, or the caller blocks on a future that never resolves"))


# -- helpers ----------------------------------------------------------------
def _recv_tokens(dotted):
    toks = set()
    for seg in (dotted or "").split("."):
        for t in seg.split("_"):
            if t:
                toks.add(t.lower())
    return toks


def _recv_ok(required, dotted):
    return not required or bool(required & _recv_tokens(dotted))


def _names_in(expr):
    for n in ast.walk(expr):
        if isinstance(n, ast.Name):
            yield n.id


# runtime-sanitizer modules whose hook calls are ownership-transparent:
# they record a handle's identity but never release or adopt it
_SANITIZER_ROOTS = ("leakcheck", "_leakcheck", "lockdep", "_lockdep")


def _is_sanitizer_call(call):
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    root = func.value
    while isinstance(root, ast.Attribute):
        root = root.value
    return isinstance(root, ast.Name) and root.id in _SANITIZER_ROOTS


class _Res:
    __slots__ = ("rid", "pair", "node", "label", "param")

    def __init__(self, rid, pair, node, label, param=None):
        self.rid = rid
        self.pair = pair
        self.node = node
        self.label = label
        self.param = param            # seeded param index (facts pass)


class _State:
    """One symbolic path: ``env`` maps a variable name (value pairs) or
    dotted receiver (receiver pairs) to a resource id; ``st`` maps the
    resource id to ``(status, info)``.  ``info`` is the line of the
    releasing call for RELEASED, or (facts pass) the set of pair names
    released so far for a seeded parameter."""

    __slots__ = ("env", "st")

    def __init__(self, env=None, st=None):
        self.env = dict(env or {})
        self.st = dict(st or {})

    def copy(self):
        return _State(self.env, self.st)

    def key(self):
        return (tuple(sorted(self.env.items())),
                tuple(sorted((k, (v[0], tuple(sorted(v[1]))
                                  if isinstance(v[1], (set, frozenset))
                                  else v[1]))
                             for k, v in self.st.items())))


class _Analysis:
    """Per-function symbolic walk.  ``seed=True`` runs the facts pass
    (parameters seeded as polymorphic handles, no findings emitted)."""

    def __init__(self, program, fi, facts, seed=False):
        self.program = program
        self.fi = fi
        self.ctx = fi.ctx
        self.facts = facts
        self.seed = seed
        self.findings = []            # (rule, anchor_node, msg)
        self.res = {}                 # rid -> _Res
        self.blown = False
        self._next_rid = 0

    # -- resource bookkeeping ----------------------------------------------
    def _new_res(self, pair, node, label, param=None):
        self._next_rid += 1
        r = _Res(self._next_rid, pair, node, label, param)
        self.res[r.rid] = r
        return r

    def _bind(self, s, key, res):
        self._unbind(s, key)
        s.env[key] = res.rid
        s.st[res.rid] = (_HELD, set() if res.param is not None else None)

    def _unbind(self, s, key):
        """Drop ``key`` and any receiver resources rooted at it."""
        pref = key + "."
        for k in [k for k in s.env if k == key or k.startswith(pref)]:
            del s.env[k]

    def _transition_release(self, s, rid, call, pair_name=None):
        res = self.res[rid]
        status, info = s.st.get(rid, (None, None))
        if res.param is not None:                      # seeded (facts pass)
            if status == _HELD:
                # replace, never mutate: forked path states share the set
                s.st[rid] = (_HELD, set(info or ())
                             | {pair_name or (res.pair and res.pair.name)})
            return
        if status == _HELD:
            s.st[rid] = (_RELEASED, call.lineno)
        elif status == _RELEASED:
            rule = "RL002" if res.pair.family == "resource" else "RL004"
            if rule == "RL002":
                msg = ("%s already released at line %d is released again "
                       "here -- a double-release corrupts the pool's free "
                       "state (the same handle returns twice)"
                       % (res.pair.describe, info))
            else:
                msg = ("settle reachable twice on one path: this %s "
                       "already reached a terminal outcome at line %d -- "
                       "the exactly-once outcome contract forbids a "
                       "second settle" % (res.pair.describe, info))
            self._finding(rule, call, msg)
        # ESCAPED: ownership was handed off; a later release is not ours
        # to judge.

    def _escape(self, s, rid):
        res = self.res[rid]
        if res.param is not None:
            s.st[rid] = (_ESCAPED, s.st.get(rid, (None, set()))[1])
            return
        # only a HELD handle can escape: a RELEASED one stays released,
        # so a later release still reads as a double-release
        if s.st.get(rid, (None, None))[0] == _HELD:
            s.st[rid] = (_ESCAPED, None)

    def _escape_name(self, s, name):
        pref = name + "."
        for k, rid in list(s.env.items()):
            if k == name or k.startswith(pref):
                self._escape(s, rid)

    def _escape_names_in(self, s, expr):
        if expr is None:
            return
        for name in set(_names_in(expr)):
            if name in s.env or any(k.startswith(name + ".")
                                    for k in s.env):
                self._escape_name(s, name)

    def _finding(self, rule, node, msg):
        self.findings.append((rule, node, msg))

    # -- call effects -------------------------------------------------------
    def _calls_in(self, expr):
        if expr is None:
            return
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                yield n

    def _apply_calls(self, expr, s, skip=None):
        skipped = set()
        for call in self._calls_in(expr):
            if call is skip or id(call) in skipped:
                continue
            if _is_sanitizer_call(call):
                # sanitizer hooks (leakcheck.track(kind, id(h)),
                # lockdep.note_blocking(...)) observe handles without
                # taking ownership -- transparent, including their
                # nested calls, or instrumenting a site would kill the
                # static tracking of that same site
                for n in ast.walk(call):
                    if isinstance(n, ast.Call):
                        skipped.add(id(n))
                continue
            self._apply_call(call, s)

    def _tracked_args(self, call, s):
        """Top-level (arg expr, env key) bindings: bare names and dotted
        attributes that are live handles; plus the set of handle names
        appearing *nested* anywhere in the arguments."""
        top, nested = [], set()
        args = list(call.args) + [k.value for k in call.keywords]
        for i, a in enumerate(args):
            key = None
            if isinstance(a, ast.Name) and a.id in s.env:
                key = a.id
            elif isinstance(a, ast.Attribute):
                d = _dotted(a)
                if d and d in s.env:
                    key = d
            if key is not None:
                top.append((i, key))
            else:
                for name in set(_names_in(a)):
                    if name in s.env or any(k.startswith(name + ".")
                                            for k in s.env):
                        nested.add(name)
        return top, nested

    def _param_index(self, callee, call, pos=None, kw=None):
        node = callee.node
        names = [a.arg for a in (list(getattr(node.args, "posonlyargs", []))
                                 + list(node.args.args))]
        if kw is not None:
            return names.index(kw) if kw in names else None
        if pos is None:                  # a **kwargs splat at the call site
            return None
        off = 1 if (callee.cls is not None
                    and isinstance(call.func, ast.Attribute)) else 0
        idx = pos + off
        return idx if idx < len(names) else None

    def _apply_call(self, call, s):
        func = call.func
        name = _terminal_name(func)
        recv = _dotted(func.value) if isinstance(func, ast.Attribute) \
            else None
        handled = set()               # env keys whose effect is decided

        # receiver-pair acquire/release on the dotted receiver
        if recv and recv not in _SELF:
            for p in _PAIRS:
                if p.kind != "receiver":
                    continue
                if name in p.acquire and not self.seed:
                    self._bind(s, recv, self._new_res(p, call, recv))
                    handled.add(recv)
                elif name in p.release:
                    rid = s.env.get(recv)
                    if rid is not None and self.res[rid].pair is p:
                        self._transition_release(s, rid, call)
                        handled.add(recv)
                    elif self.seed:
                        root = recv.split(".", 1)[0]
                        rid = s.env.get(root)
                        if rid is not None and \
                                self.res[rid].param is not None:
                            self._transition_release(s, rid, call, p.name)
                            handled.add(root)

        top, nested = self._tracked_args(call, s)

        # value-pair release: handle as argument ...
        for _, key in top:
            rid = s.env[key]
            res = self.res[rid]
            cand = [res.pair] if res.pair is not None else \
                [p for p in _PAIRS if p.kind == "value"]
            for p in cand:
                if p and name in p.release and \
                        _recv_ok(p.release_recv, recv):
                    self._transition_release(s, rid, call, p.name)
                    handled.add(key)
                    break

        # ... or as the receiver root (fut._reject(err), seq.fut._resolve())
        if recv:
            root = recv.split(".", 1)[0]
            for key in (recv, root):
                rid = s.env.get(key)
                if rid is None or key in handled:
                    continue
                res = self.res[rid]
                cand = [res.pair] if res.pair is not None else \
                    [p for p in _PAIRS if p.kind == "value"]
                matched = False
                for p in cand:
                    if p and name in p.release:
                        self._transition_release(s, rid, call, p.name)
                        handled.add(key)
                        matched = True
                        break
                if not matched and res.pair is not None:
                    # unknown method ON the handle: conservative hand-off
                    self._escape(s, rid)
                    handled.add(key)

        remaining = [(i, k) for i, k in top if k not in handled]
        if remaining or nested:
            callees = self.program._resolved.get(id(call))
            if callees is None:
                try:
                    callees = tuple(self.program.resolve_callable(
                        self.ctx, self.fi, func))
                except Exception:
                    callees = ()
            callee = callees[0] if callees and len(callees) == 1 else None
            fact = self.facts.get(id(callee.node)) if callee else None
            if fact is not None:
                n_pos = len(call.args)
                kws = [k.arg for k in call.keywords]
                for i, key in remaining:
                    if i < n_pos:
                        idx = self._param_index(callee, call, pos=i)
                    else:
                        idx = self._param_index(callee, call,
                                                kw=kws[i - n_pos])
                    rid = s.env[key]
                    res = self.res[rid]
                    if idx is None:
                        self._escape(s, rid)
                    elif res.param is not None:
                        for pn in fact["rel"].get(idx, ()):
                            self._transition_release(s, rid, call, pn)
                        if idx in fact["esc"]:
                            self._escape(s, rid)
                    elif res.pair.name in fact["rel"].get(idx, ()):
                        self._transition_release(s, rid, call)
                    elif idx in fact["esc"]:
                        self._escape(s, rid)
                    # else: resolved callee provably neither releases nor
                    # escapes it -- the handle stays OURS (one-helper-deep)
            else:
                for _, key in remaining:
                    self._escape(s, rid=s.env[key])
            for nm in nested:
                self._escape_name(s, nm)

    # -- acquires -----------------------------------------------------------
    def _acquire_in(self, value):
        """First registered acquire/ctor call in an assigned value."""
        for call in self._calls_in(value):
            name = _terminal_name(call.func)
            is_attr = isinstance(call.func, ast.Attribute)
            recv = _dotted(call.func.value) if is_attr else None
            for p in _PAIRS:
                if p.kind != "value":
                    continue
                if name in p.ctors:
                    return call, p
                if name in p.acquire:
                    if p.attr_recv_only and (recv is None
                                             or "." not in recv):
                        continue
                    if _recv_ok(p.acquire_recv, recv):
                        return call, p
        return None, None

    # -- statement walk -----------------------------------------------------
    def run(self):
        body = self.fi.node.body
        s0 = _State()
        if self.seed:
            node = self.fi.node
            names = [a.arg for a in
                     (list(getattr(node.args, "posonlyargs", []))
                      + list(node.args.args))]
            for i, nm in enumerate(names):
                if nm in _SELF:
                    continue
                self._bind(s0, nm, self._new_res(None, node, nm, param=i))
        falls, exits = self._walk(body, [s0])
        end = body[-1]
        endline = getattr(end, "end_lineno", None) or end.lineno
        for st in falls:
            exits.append(("end of function", endline, st))
        return exits

    def _dedup(self, states):
        seen, out = set(), []
        for s in states:
            k = s.key()
            if k not in seen:
                seen.add(k)
                out.append(s)
        if len(out) > _MAX_PATHS:
            self.blown = True
            out = out[:_MAX_PATHS]
        return out

    def _walk(self, stmts, states):
        exits = []
        for stmt in stmts:
            if not states:
                break
            nxt = []
            for s in states:
                falls, ex = self._step(stmt, s)
                nxt.extend(falls)
                exits.extend(ex)
            states = self._dedup(nxt)
        return states, exits

    def _refine(self, test, s):
        """Branch states for ``if test``: a *maybe-held* handle is
        non-None exactly on the branch its ``is None``/truthiness test
        excludes."""
        t, f = s.copy(), s.copy()

        def drop(state, name):
            self._unbind(state, name)

        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.left, ast.Name) and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            nm = test.left.id
            if nm in s.env:
                if isinstance(test.ops[0], ast.Is):
                    drop(t, nm)          # x is None -> not held there
                elif isinstance(test.ops[0], ast.IsNot):
                    drop(f, nm)
        elif isinstance(test, ast.Name) and test.id in s.env:
            drop(f, test.id)             # if x: -> falsy branch not held
        elif isinstance(test, ast.UnaryOp) and \
                isinstance(test.op, ast.Not) and \
                isinstance(test.operand, ast.Name) and \
                test.operand.id in s.env:
            drop(t, test.operand.id)
        return t, f

    def _step(self, stmt, s):
        T = ast
        if isinstance(stmt, (T.FunctionDef, T.AsyncFunctionDef,
                             T.ClassDef)):
            for name in set(_names_in(stmt)):
                if name in s.env or any(k.startswith(name + ".")
                                        for k in s.env):
                    self._escape_name(s, name)   # closure capture
            return [s], []

        if isinstance(stmt, (T.Assign, T.AnnAssign, T.AugAssign)):
            value = stmt.value
            if value is None:
                return [s], []
            acq_call, acq_pair = (None, None)
            targets = getattr(stmt, "targets", None) or [stmt.target]
            if not self.seed and isinstance(stmt, T.Assign):
                acq_call, acq_pair = self._acquire_in(value)
            self._apply_calls(value, s, skip=acq_call)
            if acq_call is not None:
                # the acquire's own arguments can still hand off handles
                for a in list(acq_call.args) + \
                        [k.value for k in acq_call.keywords]:
                    self._escape_names_in(s, a)
            simple_alias = (isinstance(value, T.Name)
                            and value.id in s.env)
            if simple_alias and len(targets) == 1 and \
                    isinstance(targets[0], T.Name):
                rid = s.env[value.id]
                self._unbind(s, targets[0].id)
                s.env[targets[0].id] = rid
            else:
                self._escape_names_in(s, value)
            for tgt in targets:
                for n in ast.walk(tgt):
                    if isinstance(n, (T.Subscript, T.Attribute)):
                        # index/owner expressions may mention handles
                        self._escape_names_in(s, n)
                        break
                if isinstance(tgt, T.Name):
                    self._unbind(s, tgt.id)
            if acq_call is not None and len(targets) == 1 and \
                    isinstance(targets[0], T.Name):
                nm = targets[0].id
                self._bind(s, nm, self._new_res(acq_pair, acq_call, nm))
            return [s], []

        if isinstance(stmt, T.Expr):
            self._apply_calls(stmt.value, s)
            return [s], []

        if isinstance(stmt, T.Return):
            self._apply_calls(stmt.value, s)
            self._escape_names_in(s, stmt.value)
            return [], [("return", stmt.lineno, s)]

        if isinstance(stmt, T.Raise):
            self._apply_calls(stmt.exc, s)
            self._escape_names_in(s, stmt.exc)
            return [], [("raise", stmt.lineno, s)]

        if isinstance(stmt, T.Break):
            return [], [("break", stmt.lineno, s)]
        if isinstance(stmt, T.Continue):
            return [], [("continue", stmt.lineno, s)]

        if isinstance(stmt, T.If):
            self._apply_calls(stmt.test, s)
            t, f = self._refine(stmt.test, s)
            falls_t, ex_t = self._walk(stmt.body, [t])
            falls_f, ex_f = self._walk(stmt.orelse, [f])
            return falls_t + falls_f, ex_t + ex_f

        if isinstance(stmt, (T.While, T.For, T.AsyncFor)):
            exits = []
            if isinstance(stmt, T.While):
                self._apply_calls(stmt.test, s)
            else:
                self._apply_calls(stmt.iter, s)
                for n in ast.walk(stmt.target):
                    if isinstance(n, T.Name):
                        self._unbind(s, n.id)
            body_falls, body_ex = self._walk(stmt.body, [s.copy()])
            post = [s]
            for kind, line, bs in body_ex:
                if kind == "break":
                    post.append(bs)
                elif kind == "continue":
                    body_falls.append(bs)
                else:
                    exits.append((kind, line, bs))
            for bs in body_falls:
                # iteration boundary: per-iteration RELEASED handles are
                # done with; HELD ones persist so a leak-per-iteration
                # still reaches the function exit check
                for k, rid in list(bs.env.items()):
                    if bs.st.get(rid, (None, None))[0] == _RELEASED:
                        del bs.env[k]
                post.append(bs)
            falls, ex = self._walk(stmt.orelse, self._dedup(post))
            return falls, exits + ex

        if isinstance(stmt, (T.With, T.AsyncWith)):
            for item in stmt.items:
                self._apply_calls(item.context_expr, s)
            return self._walk(stmt.body, [s])

        if isinstance(stmt, T.Try):
            falls_b, ex_b = self._walk(stmt.body, [s.copy()])
            falls_o, ex_o = self._walk(stmt.orelse, falls_b)
            h_falls, h_ex = [], []
            if stmt.handlers:
                for h in stmt.handlers:
                    f, e = self._walk(h.body, [s.copy()])
                    h_falls += f
                    h_ex += e
                # an explicit raise inside a guarded try is caught by
                # the handlers (approximated by the handler walk above)
                ex_b = [e for e in ex_b if e[0] != "raise"]
                ex_o = [e for e in ex_o if e[0] != "raise"]
            pend_falls = falls_o + h_falls
            pend_ex = ex_b + ex_o + h_ex
            if stmt.finalbody:
                out_falls, out_ex = [], []
                for st in pend_falls:
                    f2, e2 = self._walk(stmt.finalbody, [st])
                    out_falls += f2
                    out_ex += e2
                for kind, line, st in pend_ex:
                    f2, e2 = self._walk(stmt.finalbody, [st])
                    out_ex += [(kind, line, x) for x in f2] + e2
                return self._dedup(out_falls), out_ex
            return self._dedup(pend_falls), pend_ex

        if isinstance(stmt, (T.Pass, T.Import, T.ImportFrom, T.Global,
                             T.Nonlocal)):
            return [s], []

        if isinstance(stmt, (T.Delete, T.Assert)):
            for child in ast.iter_child_nodes(stmt):
                self._apply_calls(child, s)
            if isinstance(stmt, T.Delete):
                for tgt in stmt.targets:
                    if isinstance(tgt, T.Name):
                        self._unbind(s, tgt.id)
            return [s], []

        # anything we do not model (match statements, ...): stop
        # tracking everything live rather than risk a false finding
        s.env.clear()
        return [s], []


# -- program-level driver ---------------------------------------------------
def _facts_for(program):
    """Per-function, per-parameter release/escape facts, to a fixpoint.

    ``facts[id(fn def node)] = {"rel": {param_idx: set(pair names
    released on EVERY non-raising exit)}, "esc": set(param_idx escaped
    on any path)}``.
    """
    facts = {}
    for _ in range(4):
        changed = False
        for fi in program.functions:
            a = _Analysis(program, fi, facts, seed=True)
            try:
                exits = a.run()
            except RecursionError:
                exits, a.blown = [], True
            rel, esc = {}, set()
            if a.blown:
                esc = {r.param for r in a.res.values()
                       if r.param is not None}
            else:
                per_param = {}
                for r in a.res.values():
                    if r.param is None:
                        continue
                    sets = []
                    for kind, _line, st in exits:
                        status, info = st.st.get(r.rid, (None, None))
                        if status == _ESCAPED:
                            esc.add(r.param)
                        if kind == "raise":
                            continue
                        sets.append(set(info or ())
                                    if status in (_HELD, _ESCAPED)
                                    else set(info or ()))
                    if sets:
                        got = set.intersection(*sets)
                        if got:
                            per_param[r.param] = got
                rel = per_param
            prev = facts.get(id(fi.node))
            cur = {"rel": rel, "esc": esc}
            if prev != cur:
                facts[id(fi.node)] = cur
                changed = True
        if not changed:
            break
    return facts


def _lifecycle_findings(program):
    cached = getattr(program, "_lifecycle_findings", None)
    if cached is not None:
        return cached
    facts = _facts_for(program)
    findings = []
    seen = set()
    for fi in program.functions:
        a = _Analysis(program, fi, facts, seed=False)
        try:
            exits = a.run()
        except RecursionError:
            continue
        if a.blown:
            continue
        for rule, node, msg in a.findings:
            key = (rule, fi.ctx.path, node.lineno, msg)
            if key not in seen:
                seen.add(key)
                findings.append((rule, fi.ctx.path, node, msg))
        for kind, line, st in exits:
            for key, rid in st.env.items():
                status, _info = st.st.get(rid, (None, None))
                if status != _HELD:
                    continue
                res = a.res[rid]
                if res.param is not None:
                    continue
                rule = ("RL001" if res.pair.family == "resource"
                        else "RL003")
                msg = ("%s bound to '%s' is still held at the exit on "
                       "line %d (%s) -- %s"
                       % (res.pair.describe, res.label, line, kind,
                          res.pair.advice))
                dkey = (rule, fi.ctx.path, res.node.lineno, line)
                if dkey not in seen:
                    seen.add(dkey)
                    findings.append((rule, fi.ctx.path, res.node, msg))
    program._lifecycle_findings = findings
    return findings


def _yield_rule(program, rule_id):
    for rule, path, node, msg in _lifecycle_findings(program):
        if rule == rule_id:
            yield (path, node, None, msg)


@register_program_rule("RL001", Severity.ERROR,
                       "resource acquired but not released on some "
                       "exit path")
def check_resource_leak(program):
    """A declared resource (KV pages, probe slot, mesh slice, journal
    entry) is acquired on a path that then leaves the owning scope --
    via return, an explicit raise, or falling off the end -- without a
    matching release and without handing ownership off.  The PR 5
    review round shipped exactly this bug: a half-open probe slot
    leaked on the first-wins cancel path and the replica never rejoined
    rotation."""
    return _yield_rule(program, "RL001")


@register_program_rule("RL002", Severity.ERROR,
                       "double-release of an already-released resource")
def check_double_release(program):
    """The same handle reaches a second release on one path with no
    intervening re-acquire: the pool's free state now contains the
    handle twice and a later acquire can hand one resource to two
    owners."""
    return _yield_rule(program, "RL002")


@register_program_rule("RL003", Severity.ERROR,
                       "admitted future not settled on every path out "
                       "of the owning scope")
def check_unsettled_outcome(program):
    """A future created or adopted (popped from a pending queue) in
    this scope leaves it unsettled on some path: the exactly-once
    outcome contract (every admitted request gets one typed terminal
    outcome) is broken and the caller blocks forever.  This is the PR 5
    ``drain(timeout)`` bug -- workers stopped with admitted futures
    still queued -- as a rule."""
    return _yield_rule(program, "RL003")


@register_program_rule("RL004", Severity.ERROR,
                       "settle reachable twice on one path "
                       "(double-settle)")
def check_double_settle(program):
    """One path settles the same future twice.  The runtime settle
    surface is first-writer-wins, so the second outcome is silently
    dropped -- the code's intent and the delivered outcome disagree."""
    return _yield_rule(program, "RL004")
