"""CLI driver: ``python -m mxnet_tpu.lint [paths...]`` (alias
``tools/mxlint``).

Exit codes: 0 clean, 1 findings at failing severity (errors, plus
warnings under ``--strict``; with ``--baseline``, any finding not in
the ledger), 2 usage / internal error.
"""
from __future__ import annotations

import argparse
import sys

from .baseline import compare, load_baseline, write_baseline
from .core import (RULES, LintError, Severity, format_json, format_text,
                   lint_paths)


def _build_parser():
    p = argparse.ArgumentParser(
        prog="mxlint",
        description="mxnet_tpu trace-safety & concurrency static "
                    "analyzer (stdlib-only; never imports jax).")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to lint")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (default: text)")
    p.add_argument("--select", default=None, metavar="RULES",
                   help="comma list of rule ids to run (default: all)")
    p.add_argument("--disable", default=None, metavar="RULES",
                   help="comma list of rule ids to skip")
    p.add_argument("--strict", action="store_true",
                   help="warnings also fail the run (exit 1)")
    p.add_argument("--baseline", default=None, metavar="JSON",
                   help="accepted-findings ledger: only findings NOT in "
                        "the ledger fail the run (any severity)")
    p.add_argument("--write-baseline", action="store_true",
                   help="with --baseline: (re)write the ledger from the "
                        "current findings and exit 0")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--explain-guards", action="store_true",
                   help="dump the guard map the RC data-race rules "
                        "inferred for every shared attribute and exit")
    return p


def _split_rules(spec):
    if not spec:
        return None
    return {r.strip().upper() for r in spec.split(",") if r.strip()}


def _list_rules(out):
    width = max(len(r.summary) for r in RULES.values())
    for rule_id in sorted(RULES):
        r = RULES[rule_id]
        out.write("%s  %-7s  %-*s\n"
                  % (r.id, r.severity, width, r.summary))


def main(argv=None):
    parser = _build_parser()
    ns = parser.parse_args(argv)
    if ns.list_rules:
        _list_rules(sys.stdout)
        return 0
    if not ns.paths:
        parser.error("no paths given (or use --list-rules)")
    if ns.write_baseline and not ns.baseline:
        parser.error("--write-baseline requires --baseline <json>")
    if ns.explain_guards:
        from .races import format_guard_map, guard_map
        try:
            sys.stdout.write(format_guard_map(guard_map(ns.paths)) + "\n")
        except LintError as e:
            sys.stderr.write("mxlint: %s\n" % e)
            return 2
        return 0
    select = _split_rules(ns.select)
    disable = _split_rules(ns.disable)
    for spec in (select or ()), (disable or ()):
        unknown = set(spec) - set(RULES)
        if unknown:
            sys.stderr.write("mxlint: unknown rule id(s): %s\n"
                             % ", ".join(sorted(unknown)))
            return 2
    try:
        findings, n_files = lint_paths(ns.paths, select=select,
                                       disable=disable)
    except LintError as e:
        sys.stderr.write("mxlint: %s\n" % e)
        return 2
    if ns.baseline:
        if ns.write_baseline:
            n = write_baseline(findings, ns.baseline)
            sys.stdout.write("mxlint: wrote %d accepted fingerprint(s) "
                             "(%d finding(s)) to %s\n"
                             % (n, len(findings), ns.baseline))
            return 0
        try:
            ledger = load_baseline(ns.baseline)
        except (OSError, ValueError) as e:
            sys.stderr.write("mxlint: bad baseline: %s\n" % e)
            return 2
        new, accepted = compare(findings, ledger)
        if ns.format == "json":
            sys.stdout.write(format_json(new, n_files) + "\n")
        else:
            sys.stdout.write(format_text(new, n_files) + "\n")
            sys.stdout.write("baseline: %d new finding(s), %d accepted "
                             "by %s\n" % (len(new), len(accepted),
                                          ns.baseline))
        return 1 if new else 0
    if ns.format == "json":
        sys.stdout.write(format_json(findings, n_files) + "\n")
    else:
        sys.stdout.write(format_text(findings, n_files) + "\n")
    failing = {Severity.ERROR}
    if ns.strict:
        failing.add(Severity.WARNING)
    return 1 if any(f.severity in failing for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
