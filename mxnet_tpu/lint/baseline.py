"""Finding baselines: an accepted-findings ledger so new rules can land
without a same-PR zero-findings flag day.

A baseline is a JSON ledger of fingerprints ``(path, rule, message)``
with occurrence counts.  Line numbers are deliberately NOT part of the
fingerprint — unrelated edits move lines constantly; a finding only
counts as *new* when its (file, rule, message) triple appears more times
than the ledger allows.  Paths are stored relative (forward slashes) so
the ledger is stable across checkouts; absolute inputs are relativized
against ``root`` (default: the current directory).

CI contract (``ci/runtime_functions.sh lint_check``): the committed
``ci/mxlint_baseline.json`` holds the accepted findings; a run with
``--baseline`` fails on any finding not covered by the ledger, whatever
its severity — the ratchet only tightens.  Shrink the ledger by fixing
findings and rewriting it with ``--write-baseline``.
"""
from __future__ import annotations

import json
import os

__all__ = ["fingerprint", "write_baseline", "load_baseline", "compare"]

_VERSION = 1


def _norm_path(path, root=None):
    if os.path.isabs(path):
        path = os.path.relpath(path, root or os.getcwd())
    return path.replace(os.sep, "/")


def fingerprint(finding, root=None):
    """Stable identity of a finding: (relative path, rule, message)."""
    return (_norm_path(finding.path, root), finding.rule, finding.message)


def _tally(findings, root=None):
    counts = {}
    for f in findings:
        key = fingerprint(f, root)
        counts[key] = counts.get(key, 0) + 1
    return counts


def write_baseline(findings, out_path, root=None):
    """Write the accepted-findings ledger for ``findings`` (atomic
    rename; sorted and indented so diffs review cleanly)."""
    counts = _tally(findings, root)
    payload = {
        "version": _VERSION,
        "tool": "mxlint-baseline",
        "findings": [
            {"path": p, "rule": r, "message": m, "count": c}
            for (p, r, m), c in sorted(counts.items())
        ],
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out_path)
    return len(counts)


def load_baseline(path):
    """Ledger file -> {fingerprint: allowed count}.  Raises
    ``ValueError`` on a schema it does not understand."""
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("tool") != "mxlint-baseline" or \
            payload.get("version") != _VERSION:
        raise ValueError("%s is not an mxlint baseline (v%d)"
                         % (path, _VERSION))
    out = {}
    for item in payload.get("findings", ()):
        key = (item["path"], item["rule"], item["message"])
        out[key] = out.get(key, 0) + int(item.get("count", 1))
    return out


def compare(findings, baseline, root=None):
    """Split ``findings`` into (new, accepted) against the ledger.  Each
    fingerprint consumes its allowance in order; overflow occurrences —
    and fingerprints absent from the ledger — are new."""
    budget = dict(baseline)
    new, accepted = [], []
    for f in findings:
        key = fingerprint(f, root)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            accepted.append(f)
        else:
            new.append(f)
    return new, accepted
