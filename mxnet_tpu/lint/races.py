"""mxlint data-race plane: guarded-by inference + static race rules.

The lockdep plane (PR 13) proves lock *ordering* and the lifecycle
plane (PR 15) proves resource *ownership*; neither verifies that shared
mutable state is actually *guarded*.  This module closes that gap with
an Eraser-shaped static pass over the package-wide
:class:`~.interproc.Program`:

1. **Thread roots** — entry points from which a non-main thread can
   execute: ``threading.Thread(target=...)`` callables (daemon loop
   bodies included), ``do_*`` methods of ``BaseHTTPRequestHandler``
   subclasses (one root, *many* concurrent threads — it counts as two),
   and callback-registered ``on_*`` functions nothing in the package
   calls directly.  Functions reachable from no spawned root belong to
   the ``caller`` pseudo-root (public API invoked from the main/test
   thread).
2. **Guard inference** — every ``self.attr`` access (reads, writes,
   in-place container mutations, iterations) is recorded with the lock
   labels held at that point: lexical ``with lock:`` blocks plus the
   *entry-held* set of private helpers — the intersection of the locks
   held at every package call site, so a ``_foo_locked`` helper called
   only under ``self._lock`` analyzes as holding it (the one-helper-deep
   contract).  Per attribute, the majority lock among guarded accesses
   becomes the inferred guard.
3. **Rules** — each finding is anchored at the offending access and
   carries the thread-root witness chains:

   * **RC001** — attribute written from >= 2 concurrent thread roots
     with at least one post-init access holding no lock.
   * **RC002** — inconsistent guards: the same attribute is accessed
     under two disjoint lock sets (a reader under one lock cannot see
     writes under the other).
   * **RC003** — check-then-act: a value read under a lock gates a
     write that re-acquires the same lock — the guard was released
     between the read and the dependent write, so the check can go
     stale.
   * **RC004** — a container iterated in one thread root while mutated
     in another with no common lock (``RuntimeError: dictionary changed
     size`` at best, silent corruption at worst).

Intent annotations (distinct from ``# mxlint: disable`` suppressions —
they feed the *inference*, not the reporter) go on the attribute's
assignment line:

* ``# mxlint: guarded-by(self._lock)`` — declares the guard, overriding
  majority inference; accesses under a different lock become RC002.
* ``# mxlint: not-shared`` — declares the attribute single-threaded or
  externally synchronized (rationale prose welcome after an em-dash);
  all RC rules skip it.

``python -m mxnet_tpu.lint --explain-guards <paths>`` dumps the
inferred guard map (:func:`guard_map` / :func:`format_guard_map`).

The dynamic half is :mod:`mxnet_tpu.racecheck` — a runtime lockset
sanitizer catching the races this pass cannot see (getattr indirection,
foreign callbacks).  Like the rest of mxlint this module is
stdlib-only and never imports jax.
"""
from __future__ import annotations

import ast
import re

from .core import Severity, register_program_rule
from .rules import (CALLBACK_PREFIXES, _lock_exprs, _lockish,
                    _terminal_name, _thread_creations)

__all__ = ["guard_map", "format_guard_map"]

# race findings carry two witness chains plus the remediation advice —
# the interproc 220-char why-cap would truncate the actionable tail, so
# RC messages get their own wider cap
_MAX_MSG = 480


def _clip(msg):
    return msg if len(msg) <= _MAX_MSG else msg[:_MAX_MSG] + "..."

# container methods that mutate the receiver in place
_MUTATORS = frozenset({
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update",
})
# builtins whose call iterates their first argument
_ITERATING_BUILTINS = frozenset({
    "sorted", "list", "tuple", "set", "frozenset", "dict", "min", "max",
    "sum", "any", "all",
})
# iterating view methods: for k in self.d.items() / values() / keys()
_VIEW_METHODS = frozenset({"items", "values", "keys"})
# HTTP-handler base classes: their do_*/handle methods run one thread
# per connection — a single root that is concurrent with itself
_HANDLER_BASES = frozenset({
    "BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
    "BaseRequestHandler", "StreamRequestHandler",
})
_HANDLER_METHODS = ("do_",)
_DUNDER = re.compile(r"^__.*__$")
_MAX_CHAIN = 4          # witness-chain hops shown per root
_ENTRY_ROUNDS = 8       # entry-held fixpoint cap

_ANNOTATION = re.compile(
    r"#\s*mxlint:\s*(?:guarded-by\(\s*(?P<guard>[^)]+?)\s*\)"
    r"|(?P<notshared>not-shared))")


class _Root:
    """One thread entry point: the FunctionInfo it starts in, its kind
    ('thread' / 'handler' / 'callback' / 'caller'), and its concurrency
    weight (how many simultaneous threads it stands for)."""

    __slots__ = ("fi", "kind", "weight", "label")

    def __init__(self, fi, kind, weight, label):
        self.fi = fi
        self.kind = kind
        self.weight = weight
        self.label = label

    def __repr__(self):
        return "_Root(%s %s)" % (self.kind, self.label)


class _Access:
    """One attribute access: where, what kind, under which locks, from
    which thread roots."""

    __slots__ = ("cls_key", "attr", "kind", "fi", "node", "line", "col",
                 "held", "in_init", "with_node")

    def __init__(self, cls_key, attr, kind, fi, node, held, in_init,
                 with_node=None):
        self.cls_key = cls_key
        self.attr = attr
        self.kind = kind          # 'read' | 'write' | 'mut' | 'iter'
        self.fi = fi
        self.node = node
        self.line = node.lineno
        self.col = node.col_offset
        self.held = held          # frozenset of lock labels
        self.in_init = in_init
        self.with_node = with_node  # innermost lock With, or None


def _is_write_kind(kind):
    return kind in ("write", "mut")


# ---------------------------------------------------------------------------
# thread roots + reachability
# ---------------------------------------------------------------------------
def _call_sites(program):
    """callee FunctionInfo -> [(caller fi, Call node, held labels)]."""
    sites = {}
    for fi in program.functions:
        for call, held in fi.calls:
            for callee in program._resolved.get(id(call), ()):
                sites.setdefault(callee, []).append((fi, call, held))
    return sites


def _thread_name_kwarg(call):
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _handler_classes(ctx):
    """Class names in this module subclassing an HTTP/socket handler."""
    out = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for base in node.bases:
            if _terminal_name(base) in _HANDLER_BASES:
                out.add(node.name)
    return out


def _find_roots(program, call_sites):
    """Every spawned-thread entry point in the program."""
    roots = []
    seen = set()

    def add(fi, kind, weight, label):
        if fi is None or id(fi) in seen:
            return
        seen.add(id(fi))
        roots.append(_Root(fi, kind, weight, label))

    for ctx in program.contexts:
        for call, _daemon, target in _thread_creations(ctx):
            if target is None:
                continue
            caller = ctx._enclosing_fn(call)
            caller_fi = program.by_node.get(id(caller)) \
                if caller is not None else None
            for fi in program.resolve_callable(ctx, caller_fi, target):
                tname = _thread_name_kwarg(call)
                add(fi, "thread", 1,
                    "%s%s" % (fi.qualname,
                              " (%r)" % tname if tname else ""))
        handlers = _handler_classes(ctx)
        if handlers:
            for fi in program.functions:
                if fi.ctx is ctx and fi.cls in handlers and \
                        fi.name.startswith(_HANDLER_METHODS):
                    add(fi, "handler", 2, fi.qualname)
    # callback-registered functions: on_*/_on_* defs that nothing in the
    # package calls directly are invoked from foreign threads
    for fi in program.functions:
        if fi.name.startswith(CALLBACK_PREFIXES) and \
                fi not in call_sites and id(fi) not in seen:
            add(fi, "callback", 1, fi.qualname)
    return roots


def _reachable(root, program):
    """fi -> qualname chain from the root, by BFS over resolved calls."""
    chains = {root.fi: (root.fi.qualname,)}
    frontier = [root.fi]
    while frontier:
        nxt = []
        for fi in frontier:
            base = chains[fi]
            for call, _held in fi.calls:
                for callee in program._resolved.get(id(call), ()):
                    if callee in chains:
                        continue
                    chains[callee] = base + (callee.qualname,)
                    nxt.append(callee)
        frontier = nxt
    return chains


def _chain_text(root, chains, fi):
    chain = chains.get(fi, (fi.qualname,))
    if len(chain) > _MAX_CHAIN:
        chain = chain[:1] + ("...",) + chain[-(_MAX_CHAIN - 2):]
    return "%s %s" % (root.kind, " -> ".join(chain))


# ---------------------------------------------------------------------------
# entry-held lock sets (the one-helper-deep contract)
# ---------------------------------------------------------------------------
def _entry_held(program, call_sites):
    """fi -> locks provably held on EVERY package call path into it.

    Only private (underscore) functions with at least one package call
    site qualify — a public method is part of the API surface and may be
    entered bare from anywhere, whatever internal callers hold."""
    entry = {}
    for _ in range(_ENTRY_ROUNDS):
        changed = False
        for fi, sites in call_sites.items():
            if not fi.name.startswith("_") or _DUNDER.match(fi.name):
                continue
            held_sets = [
                frozenset(held) | entry.get(caller, frozenset())
                for caller, _call, held in sites]
            new = frozenset.intersection(*held_sets) if held_sets \
                else frozenset()
            if new != entry.get(fi, frozenset()):
                entry[fi] = new
                changed = True
        if not changed:
            break
    return entry


# ---------------------------------------------------------------------------
# attribute access collection
# ---------------------------------------------------------------------------
def _self_aliases(ctx):
    """Module-wide ``name -> class`` map from ``name = self`` bindings
    (the ``gw = self`` closure idiom nested HTTP handlers use)."""
    aliases = {}
    dropped = set()
    for fi_node in ctx.functions:
        cls = ctx.class_of.get(id(fi_node))
        if cls is None:
            continue
        for node in ast.walk(fi_node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if tgt.id in aliases and aliases[tgt.id] != cls:
                            dropped.add(tgt.id)
                        aliases[tgt.id] = cls
    for name in dropped:
        aliases.pop(name, None)
    return aliases


def _selfish_attr(node, fi, aliases):
    """(cls_name, attr) when ``node`` is ``self.X`` / ``cls.X`` of the
    enclosing class, or ``alias.X`` through a ``alias = self`` binding;
    None otherwise."""
    if not isinstance(node, ast.Attribute):
        return None
    base = node.value
    if not isinstance(base, ast.Name):
        return None
    if base.id in ("self", "cls"):
        return (fi.cls, node.attr) if fi.cls else None
    cls = aliases.get(base.id)
    if cls is not None and base.id not in fi.ctx.params_of(fi.node):
        return cls, node.attr
    return None


def _iter_source_attr(expr, fi, aliases):
    """The (cls, attr) a for/comprehension/builtin iterates, if it is a
    selfish attribute (directly or through .items()/.values()/.keys())."""
    if isinstance(expr, ast.Call) and \
            isinstance(expr.func, ast.Attribute) and \
            expr.func.attr in _VIEW_METHODS and not expr.args:
        expr = expr.func.value
    return _selfish_attr(expr, fi, aliases)


def _collect_accesses(program, entry_held):
    """Every selfish attribute access in the program, with held locks."""
    accesses = []
    alias_cache = {}
    for fi in program.functions:
        if _DUNDER.match(fi.name) and fi.name != "__init__":
            continue
        ctx = fi.ctx
        aliases = alias_cache.get(id(ctx))
        if aliases is None:
            aliases = alias_cache[id(ctx)] = _self_aliases(ctx)
        in_init = fi.name == "__init__"
        entry = entry_held.get(fi, frozenset())

        def note(node, cls, attr, kind, held, with_node):
            accesses.append(_Access(
                (ctx.module_stem, cls), attr, kind, fi, node,
                frozenset(held), in_init, with_node))

        def visit(node, held, with_node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef,
                                      ast.Lambda)):
                    continue  # nested defs analyzed on their own
                new_held, new_with = held, with_node
                if isinstance(child, ast.With):
                    labels = [program._lock_label(e, fi)
                              for e in _lock_exprs(child)]
                    fresh = [l for l in labels if l not in held]
                    if fresh:
                        new_held = held | frozenset(fresh)
                        new_with = child
                if isinstance(child, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)):
                    tgts = child.targets if isinstance(child, ast.Assign) \
                        else [child.target]
                    for tgt in tgts:
                        got = _selfish_attr(tgt, fi, aliases)
                        if got is not None:
                            note(tgt, got[0], got[1], "write", held,
                                 with_node)
                        elif isinstance(tgt, ast.Subscript):
                            got = _selfish_attr(tgt.value, fi, aliases)
                            if got is not None:
                                note(tgt, got[0], got[1], "mut", held,
                                     with_node)
                elif isinstance(child, ast.Delete):
                    for tgt in child.targets:
                        got = _selfish_attr(tgt, fi, aliases)
                        if got is not None:
                            note(tgt, got[0], got[1], "write", held,
                                 with_node)
                        elif isinstance(tgt, ast.Subscript):
                            got = _selfish_attr(tgt.value, fi, aliases)
                            if got is not None:
                                note(tgt, got[0], got[1], "mut", held,
                                     with_node)
                elif isinstance(child, ast.Call):
                    func = child.func
                    if isinstance(func, ast.Attribute) and \
                            func.attr in _MUTATORS:
                        got = _selfish_attr(func.value, fi, aliases)
                        if got is not None:
                            note(child, got[0], got[1], "mut", held,
                                 with_node)
                    elif isinstance(func, ast.Name) and \
                            func.id in _ITERATING_BUILTINS and child.args:
                        got = _iter_source_attr(child.args[0], fi,
                                                aliases)
                        if got is not None:
                            note(child, got[0], got[1], "iter", held,
                                 with_node)
                elif isinstance(child, ast.For):
                    got = _iter_source_attr(child.iter, fi, aliases)
                    if got is not None:
                        note(child.iter, got[0], got[1], "iter", held,
                             with_node)
                elif isinstance(child, (ast.ListComp, ast.SetComp,
                                        ast.DictComp, ast.GeneratorExp)):
                    for gen in child.generators:
                        got = _iter_source_attr(gen.iter, fi, aliases)
                        if got is not None:
                            note(gen.iter, got[0], got[1], "iter", held,
                                 with_node)
                elif isinstance(child, ast.Attribute) and \
                        isinstance(child.ctx, ast.Load):
                    got = _selfish_attr(child, fi, aliases)
                    if got is not None:
                        note(child, got[0], got[1], "read", held,
                             with_node)
                visit(child, new_held, new_with)

        visit(fi.node, entry, None)
    return accesses


# ---------------------------------------------------------------------------
# lock aliasing: a Condition shares its underlying Lock
# ---------------------------------------------------------------------------
def _lock_aliases(program):
    """label -> canonical label, from ``self._cv =
    threading.Condition(self._lock)`` bindings: the Condition and the
    lock it wraps are ONE mutex, so ``with self._cv:`` and ``with
    self._lock:`` exclude each other and must unify for guard
    inference."""
    alias = {}
    for fi in program.functions:
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and node.value.args
                    and _terminal_name(node.value.func) == "Condition"):
                continue
            src = program._lock_label(node.value.args[0], fi)
            for tgt in node.targets:
                if isinstance(tgt, (ast.Attribute, ast.Name)):
                    dst = program._lock_label(tgt, fi)
                    if src and dst and src != dst:
                        alias[dst] = src

    def resolve(label):
        seen = set()
        while label in alias and label not in seen:
            seen.add(label)
            label = alias[label]
        return label

    return {k: resolve(k) for k in alias}


# ---------------------------------------------------------------------------
# intent annotations
# ---------------------------------------------------------------------------
def _canon_guard(raw, cls_key, ctx):
    """Canonicalize a guarded-by(<lock>) value to the interproc lock
    label space: ``self._lock`` -> ``mod.Cls._lock``; a bare name ->
    module global; an already-dotted label passes through."""
    raw = raw.strip()
    if raw.startswith("self.") or raw.startswith("cls."):
        return "%s.%s.%s" % (cls_key[0], cls_key[1],
                             raw.split(".", 1)[1])
    if "." in raw:
        return raw
    return "%s.%s" % (cls_key[0], raw)


def _annotations(program):
    """(cls_key, attr) -> ('not-shared', None) | ('guarded-by', label),
    read from assignment-line comments."""
    out = {}
    for fi in program.functions:
        ctx = fi.ctx
        if fi.cls is None:
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                continue
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in tgts:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in ("self", "cls")):
                    continue
                # the comment may sit on any physical line of a
                # multiline assignment (continuation/parenthesized)
                m = None
                for line in range(node.lineno,
                                  getattr(node, "end_lineno",
                                          node.lineno) + 1):
                    if line - 1 >= len(ctx.lines):
                        break
                    m = _ANNOTATION.search(ctx.lines[line - 1])
                    if m is not None:
                        break
                if m is None:
                    continue
                key = ((ctx.module_stem, fi.cls), tgt.attr)
                if m.group("notshared"):
                    out[key] = ("not-shared", None)
                else:
                    out[key] = ("guarded-by", _canon_guard(
                        m.group("guard"), key[0], ctx))
    return out


# ---------------------------------------------------------------------------
# the shared analysis
# ---------------------------------------------------------------------------
class _RaceState:
    __slots__ = ("roots", "chains", "by_attr", "annotations",
                 "roots_of_cache", "findings")

    def __init__(self):
        self.roots = []
        self.chains = {}          # root -> {fi: chain tuple}
        self.by_attr = {}         # (cls_key, attr) -> [_Access]
        self.annotations = {}
        self.roots_of_cache = {}
        self.findings = {}        # rule id -> [(path, node, col, msg)]

    def roots_of(self, fi):
        """Spawned roots reaching ``fi`` (or the caller pseudo-root)."""
        got = self.roots_of_cache.get(fi)
        if got is None:
            got = tuple(r for r in self.roots
                        if r.kind != "caller" and fi in self.chains[r])
            if not got:
                got = (self.roots[-1],)   # the caller pseudo-root
            self.roots_of_cache[fi] = got
        return got

    def chain(self, root, fi):
        if root.kind == "caller":
            return "caller %s" % fi.qualname
        return _chain_text(root, self.chains[root], fi)


def _race_state(program):
    got = getattr(program, "_race_state_cache", None)
    if got is not None:
        return got
    program.finalize()
    state = _RaceState()
    call_sites = _call_sites(program)
    state.roots = _find_roots(program, call_sites)
    for root in state.roots:
        state.chains[root] = _reachable(root, program)
    # the caller pseudo-root, always last (see roots_of)
    state.roots.append(_Root(None, "caller", 1, "caller"))
    entry = _entry_held(program, call_sites)
    aliases = _lock_aliases(program)
    self_alias_cache = {}
    for acc in _collect_accesses(program, entry):
        if _lockish(ast.Name(id=acc.attr)):
            continue   # the lock objects themselves: assigned once,
            #            then only read — not shared *data*
        if acc.held:
            ctx = acc.fi.ctx
            amap = self_alias_cache.get(id(ctx))
            if amap is None:
                amap = self_alias_cache[id(ctx)] = _self_aliases(ctx)
            held = set()
            for label in acc.held:
                # unify `with gw._lock:` (alias = self closure) with
                # the canonical `mod.Cls._lock` label
                head, _, rest = label.partition(".")
                if rest and head in amap:
                    label = "%s.%s.%s" % (ctx.module_stem, amap[head],
                                          rest)
                held.add(aliases.get(label, label))
            acc.held = frozenset(held)
        state.by_attr.setdefault((acc.cls_key, acc.attr),
                                 []).append(acc)
    state.annotations = {
        key: (kind, aliases.get(label, label) if label else None)
        for key, (kind, label) in _annotations(program).items()}
    _run_rules(program, state)
    program._race_state_cache = state
    return state


def _attr_label(cls_key, attr):
    return "%s.%s" % (cls_key[1], attr)


def _lock_desc(held):
    if not held:
        return "no lock"
    return " + ".join("'%s'" % l for l in sorted(held))


def _majority_guard(accesses, annotation):
    """The inferred guard label: the annotation when present, else the
    most common lock label among guarded accesses (ties -> sorted
    first)."""
    if annotation is not None and annotation[0] == "guarded-by":
        return annotation[1]
    counts = {}
    for acc in accesses:
        for label in acc.held:
            counts[label] = counts.get(label, 0) + 1
    if not counts:
        return None
    top = max(counts.values())
    return sorted(l for l, n in counts.items() if n == top)[0]


def _access_word(kind):
    return {"write": "write", "mut": "mutation", "read": "read",
            "iter": "iteration"}[kind]


def _witnesses(state, accesses, prefer=()):
    """Up to two distinct root chains covering these accesses, writers
    first."""
    ordered = []
    for acc in list(prefer) + list(accesses):
        for root in state.roots_of(acc.fi):
            entry = (root, acc.fi)
            if entry not in ordered:
                ordered.append(entry)
    texts = []
    seen_roots = set()
    for root, fi in ordered:
        if id(root) in seen_roots:
            continue
        seen_roots.add(id(root))
        texts.append(state.chain(root, fi))
        if len(texts) == 2:
            break
    return texts


def _run_rules(program, state):
    rc1, rc2, rc3, rc4 = [], [], [], []
    for (cls_key, attr), accesses in sorted(
            state.by_attr.items(),
            key=lambda kv: (kv[0][0][0], kv[0][0][1], kv[0][1])):
        annotation = state.annotations.get((cls_key, attr))
        if annotation is not None and annotation[0] == "not-shared":
            continue
        live = [a for a in accesses if not a.in_init]
        if not live:
            continue
        guard = _majority_guard(live, annotation)

        # RC001: written from >= 2 concurrent roots, >= 1 bare access
        writers = [a for a in live if _is_write_kind(a.kind)]
        writer_roots = {}
        for a in writers:
            for root in state.roots_of(a.fi):
                writer_roots[id(root)] = root
        weight = sum(r.weight for r in writer_roots.values())
        spawned = any(r.kind != "caller" for r in writer_roots.values())
        if weight >= 2 and spawned:
            bare = sorted((a for a in live if not a.held),
                          key=lambda a: (a.fi.ctx.path, a.line, a.col))
            if bare:
                a = bare[0]
                wits = _witnesses(state, live, prefer=writers)
                hint = " (majority guard: '%s')" % guard if guard else ""
                rc1.append((a.fi.ctx.path, a.node, None, _clip(
                    "shared attribute '%s' is written from %d concurrent"
                    " thread roots with an unguarded %s here%s; "
                    "witnesses: %s. Guard every post-init access with "
                    "one lock, or annotate its init-site "
                    "'# mxlint: not-shared'."
                    % (_attr_label(cls_key, attr), weight,
                       _access_word(a.kind), hint, " | ".join(wits)))))

        # RC002: two disjoint non-empty guard sets on one attribute
        guarded = [a for a in live if a.held]
        if guard is not None and guarded:
            all_roots = {}
            for a in live:
                for root in state.roots_of(a.fi):
                    all_roots[id(root)] = root
            total_weight = sum(r.weight for r in all_roots.values())
            if total_weight >= 2:
                odd = sorted((a for a in guarded if guard not in a.held),
                             key=lambda a: (a.fi.ctx.path, a.line,
                                            a.col))
                if odd:
                    a = odd[0]
                    n_major = sum(1 for x in guarded if guard in x.held)
                    rc2.append((a.fi.ctx.path, a.node, None, _clip(
                        "inconsistent guards for attribute '%s': %d "
                        "access(es) hold '%s' but this %s holds %s; a "
                        "thread under one lock cannot exclude writers "
                        "under the other. Guard every access with one "
                        "lock, or declare the intent "
                        "'# mxlint: guarded-by(<lock>)'."
                        % (_attr_label(cls_key, attr), n_major, guard,
                           _access_word(a.kind), _lock_desc(a.held)))))

        # RC004: iterated in one root, mutated in another, no common lock
        iters = [a for a in live if a.kind == "iter"]
        muts = [a for a in live if a.kind == "mut"]
        hit = None
        for it in sorted(iters, key=lambda a: (a.fi.ctx.path, a.line)):
            for mu in sorted(muts,
                             key=lambda a: (a.fi.ctx.path, a.line)):
                if it.held & mu.held:
                    continue
                it_roots = state.roots_of(it.fi)
                mu_roots = state.roots_of(mu.fi)
                disjointish = [
                    (ri, rm) for ri in it_roots for rm in mu_roots
                    if ri is not rm or ri.weight >= 2]
                if disjointish:
                    hit = (it, mu, disjointish[0])
                    break
            if hit:
                break
        if hit is not None:
            it, mu, (ri, rm) = hit
            rc4.append((it.fi.ctx.path, it.node, None, _clip(
                "container attribute '%s' is iterated under %s in [%s] "
                "but mutated under %s in [%s] with no common lock: "
                "concurrent mutation corrupts the iteration "
                "(RuntimeError: changed size, or skipped entries). "
                "Guard both sides with one lock, or iterate a snapshot "
                "taken under it."
                % (_attr_label(cls_key, attr), _lock_desc(it.held),
                   state.chain(ri, it.fi), _lock_desc(mu.held),
                   state.chain(rm, mu.fi)))))

        # RC003: check-then-act across a released guard (per function)
        rc3.extend(_check_then_act(program, cls_key, attr, live))

    state.findings = {"RC001": rc1, "RC002": rc2, "RC003": rc3,
                      "RC004": rc4}


def _check_then_act(program, cls_key, attr, accesses):
    """Detect: value read from the attribute under lock L and bound to a
    name; a later ``if`` on that name gates a write to the same
    attribute under a *new* acquisition of L."""
    out = []
    by_fn = {}
    for a in accesses:
        by_fn.setdefault(a.fi, []).append(a)
    for fi, accs in by_fn.items():
        ctx = fi.ctx
        reads = []   # (bound name, access)
        for a in accs:
            if a.kind != "read" or not a.held or a.with_node is None:
                continue
            # the read must feed an Assign to a simple name
            p = ctx._parents.get(a.node)
            while p is not None and not isinstance(p, ast.stmt):
                p = ctx._parents.get(p)
            if isinstance(p, ast.Assign) and len(p.targets) == 1 and \
                    isinstance(p.targets[0], ast.Name):
                # ownership transfer, not a stale check: ``x =
                # self.pool.popleft()`` REMOVES the value under the
                # lock, so a later compensating write gated on x is
                # safe by construction — skip mutator-sourced binds
                if isinstance(p.value, ast.Call) and \
                        isinstance(p.value.func, ast.Attribute) and \
                        p.value.func.attr in _MUTATORS:
                    continue
                reads.append((p.targets[0].id, a))
        if not reads:
            continue
        for a in accs:
            if not _is_write_kind(a.kind) or not a.held or \
                    a.with_node is None:
                continue
            for name, r in reads:
                if a.with_node is r.with_node or a.line <= r.line:
                    continue
                if not (a.held & r.held):
                    continue
                # the write must sit under an if testing the bound name,
                # and that if must start after the read's with closed
                gate = None
                p = ctx._parents.get(a.node)
                while p is not None and p is not fi.node:
                    if isinstance(p, ast.If) and any(
                            isinstance(n, ast.Name) and n.id == name
                            for n in ast.walk(p.test)):
                        gate = p
                    p = ctx._parents.get(p)
                if gate is None or \
                        gate.lineno < getattr(r.with_node, "end_lineno",
                                              r.with_node.lineno):
                    continue
                out.append((ctx.path, a.node, None, _clip(
                    "check-then-act on attribute '%s': the value read "
                    "under %s at line %d gates this %s, but the lock "
                    "was released in between — the check can go stale "
                    "before the write lands. Do the read and the "
                    "dependent write in one critical section (or "
                    "re-validate under the lock)."
                    % (_attr_label(cls_key, attr), _lock_desc(r.held),
                       r.line, _access_word(a.kind)))))
                break
    return out


def _yield_rule(program, rule_id):
    for hit in _race_state(program).findings.get(rule_id, ()):
        yield hit


@register_program_rule("RC001", Severity.ERROR,
                       "shared attribute written without its guard")
def check_unguarded_shared_write(program):
    """An attribute written from two or more concurrent thread roots
    must hold one lock at every post-init access; a bare ``+=`` from a
    handler thread silently loses increments under the GIL's bytecode
    interleaving, and bare container writes corrupt readers.  The
    finding is anchored at the unguarded access and names both thread
    roots' witness chains."""
    return _yield_rule(program, "RC001")


@register_program_rule("RC002", Severity.ERROR,
                       "inconsistent guards on one attribute")
def check_inconsistent_guards(program):
    """Accesses to one attribute under two different locks exclude
    nothing: each critical section only excludes threads taking the
    SAME lock.  The finding fires at the minority-lock access, with the
    majority (or annotated) guard named."""
    return _yield_rule(program, "RC002")


@register_program_rule("RC003", Severity.ERROR,
                       "check-then-act across a released guard")
def check_check_then_act(program):
    """Reading a value under a lock, releasing it, then writing based on
    that value under a re-acquired lock is atomic-looking but racy: the
    attribute can change between the two critical sections.  Fires at
    the dependent write."""
    return _yield_rule(program, "RC003")


@register_program_rule("RC004", Severity.ERROR,
                       "container iterated and mutated with no common "
                       "lock")
def check_iter_vs_mutate(program):
    """A dict/set/list iterated in one thread root while another root
    mutates it throws ``RuntimeError: ... changed size during
    iteration`` at best — and at worst the iteration silently skips or
    repeats entries.  Fires at the iteration with both witness
    chains."""
    return _yield_rule(program, "RC004")


# ---------------------------------------------------------------------------
# --explain-guards
# ---------------------------------------------------------------------------
def guard_map(paths):
    """Build the program over ``paths`` and return the inferred guard
    map: ``{ 'mod.Cls.attr': {guard, guarded, unguarded, annotation,
    roots} }`` (the ``--explain-guards`` payload)."""
    from .core import _Entry, iter_python_files
    from .interproc import Program

    program = Program()
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            entry = _Entry(f.read(), path)
        if entry.tree is not None and not entry.skip:
            program.add_module(entry.tree, entry.path, entry.lines)
    program.finalize()
    state = _race_state(program)
    out = {}
    for (cls_key, attr), accesses in state.by_attr.items():
        live = [a for a in accesses if not a.in_init]
        if not live:
            continue
        annotation = state.annotations.get((cls_key, attr))
        guard = _majority_guard(live, annotation)
        roots = {}
        for a in live:
            for root in state.roots_of(a.fi):
                roots[id(root)] = "%s(%s)" % (root.kind, root.label)
        key = "%s.%s.%s" % (cls_key[0], cls_key[1], attr)
        out[key] = {
            "guard": guard,
            "guarded": sum(1 for a in live if a.held),
            "unguarded": sum(1 for a in live if not a.held),
            "annotation": None if annotation is None else (
                annotation[0] if annotation[1] is None
                else "%s(%s)" % annotation),
            "roots": sorted(roots.values()),
        }
    return out


def format_guard_map(mapping):
    """Human-readable --explain-guards dump, one attribute per line."""
    lines = ["== inferred guard map (%d shared attribute(s)) =="
             % len(mapping)]
    for key in sorted(mapping):
        info = mapping[key]
        bits = ["guard=%s" % (info["guard"] or "-"),
                "%d guarded / %d unguarded" % (info["guarded"],
                                               info["unguarded"])]
        if info["annotation"]:
            bits.append("annotated %s" % info["annotation"])
        bits.append("roots: %s" % ", ".join(info["roots"]))
        lines.append("%-48s %s" % (key, "  ".join(bits)))
    return "\n".join(lines)
