"""mxlint — trace-safety & concurrency static analyzer.

The two highest-risk bug classes in a TPU-native JAX/XLA framework are
invisible until production: host transfers and Python side effects
captured inside traced/jitted regions (silent recompiles, wrong numerics,
100x slowdowns), and races in the async host-side layers.  JAX's tracing
model makes these hazards *statically* detectable from the AST — a traced
function runs exactly once per shape signature, so anything impure inside
it is either baked in as a constant, silently dropped, or a
ConcretizationError waiting for a new shape.

Usage::

    python -m mxnet_tpu.lint mxnet_tpu/ example/ tools/
    python -m mxnet_tpu.lint --list-rules
    python -m mxnet_tpu.lint path.py --format json

Rules (docs/STATIC_ANALYSIS.md has the full catalog + fix patterns):

=====  ========  =====================================================
TS001  error     host sync (.asnumpy()/.item()/float()/np.asarray)
                 inside traced code
TS002  error     trace-time side effect (attribute mutation, print,
                 time.time(), container append) in a traced body
TS003  error     untracked randomness (np.random / stdlib random)
                 inside traced code — use mxnet_tpu.random
TS004  warning   Python control flow branching on a tracer-valued
                 expression (recompile / ConcretizationError trap)
TS005  error     use-after-donate: a buffer read after being passed
                 through a donating jit call in the same scope
CC001  error     lock held across a blocking call (recv/join/sleep/
                 sendall/connect)
CC002  error     non-daemon thread with no join path
=====  ========  =====================================================

Suppress a finding with a trailing (or immediately preceding standalone)
comment ``# mxlint: disable=TS002`` (comma list, or ``disable=all``);
``# mxlint: skip-file`` skips a whole file.  Suppressions should carry a
rationale — they are audit points, not escape hatches.

The static analyzer is complemented by a *runtime* trace guard
(``MXNET_TRACE_GUARD=warn|raise``, see ``mxnet_tpu.dispatch``) that
catches the host syncs static analysis cannot prove, e.g. through
aliases, getattr indirection, or dynamically-built callables.

This package is stdlib-only (ast + tokenize): linting never imports jax
or initializes a backend.
"""
from __future__ import annotations

from .core import (  # noqa: F401
    RULES,
    Finding,
    LintError,
    Rule,
    Severity,
    format_json,
    format_text,
    lint_file,
    lint_paths,
    lint_source,
    register_rule,
)
from . import rules as _rules  # noqa: F401  (registers the rule set)

__all__ = ["RULES", "Finding", "LintError", "Rule", "Severity",
           "format_json", "format_text", "lint_file", "lint_paths",
           "lint_source", "register_rule"]
