"""mxlint — trace-safety & concurrency static analyzer.

The two highest-risk bug classes in a TPU-native JAX/XLA framework are
invisible until production: host transfers and Python side effects
captured inside traced/jitted regions (silent recompiles, wrong numerics,
100x slowdowns), and races in the async host-side layers.  JAX's tracing
model makes these hazards *statically* detectable from the AST — a traced
function runs exactly once per shape signature, so anything impure inside
it is either baked in as a constant, silently dropped, or a
ConcretizationError waiting for a new shape.

Usage::

    python -m mxnet_tpu.lint mxnet_tpu/ example/ tools/
    python -m mxnet_tpu.lint --list-rules
    python -m mxnet_tpu.lint path.py --format json

Rules (docs/STATIC_ANALYSIS.md has the full catalog + fix patterns):

=====  ========  =====================================================
TS001  error     host sync (.asnumpy()/.item()/float()/np.asarray)
                 inside traced code
TS002  error     trace-time side effect (attribute mutation, print,
                 time.time(), container append) in a traced body
TS003  error     untracked randomness (np.random / stdlib random)
                 inside traced code — use mxnet_tpu.random
TS004  warning   Python control flow branching on a tracer-valued
                 expression (recompile / ConcretizationError trap)
TS005  error     use-after-donate: a buffer read after being passed
                 through a donating jit call in the same scope
TS007  error     dict/list/set in a static_argnums position of
                 TrackedJit/jit (unhashable cache key; retrace storm)
CC001  error     lock held across a blocking call (recv/join/sleep/
                 sendall/connect) — inter-procedural: a helper that
                 blocks taints every caller invoking it under a lock
CC002  error     non-daemon thread with no join path
CC003  error     lock-order inversion: a cycle in the package-wide
                 acquisition-order graph, both witness paths reported
CC004  error     user callback (on_*) or Future settle (set_result/
                 set_exception) invoked while holding a lock
CC005  warning   raw socket I/O or an unbounded wait reachable from a
                 registered daemon-loop body (heartbeat/control ticks)
RL001  error     resource (KV pages / probe slot / mesh slice /
                 journal entry) acquired but not released on some
                 exit path (raise / early return / fall-through)
RL002  error     double-release: the same handle released twice on
                 one path with no intervening re-acquire
RL003  error     future created or admitted but not settled on every
                 path out of the owning scope (the PR 5 drain bug,
                 as a rule)
RL004  error     settle reachable twice on one path (double-settle)
RC001  error     shared attribute written from >= 2 concurrent thread
                 roots with an unguarded access (guard inferred from
                 the majority of lock-held accesses)
RC002  error     inconsistent guards: one attribute accessed under two
                 different locks (neither excludes the other)
RC003  error     check-then-act: a value read under a lock gates a
                 write that re-acquires it — the check can go stale
RC004  error     container iterated in one thread root while another
                 mutates it with no common lock
=====  ========  =====================================================

The RC rules (:mod:`~mxnet_tpu.lint.races`) infer each attribute's
guard from the majority of accesses made under a held lock (the
``--explain-guards`` CLI dump shows the inferred map) and honor two
*intent annotations* on the attribute's assignment line:
``# mxlint: guarded-by(self._lock)`` declares the guard (overriding
inference) and ``# mxlint: not-shared`` exempts a single-threaded or
externally-synchronized attribute.  The runtime half is
:mod:`mxnet_tpu.racecheck` (``MXTPU_RACECHECK=record|raise``), an
Eraser-style lockset sanitizer over instrumented classes.

The RL rules are driven by a declarative pair registry
(:mod:`~mxnet_tpu.lint.lifecycle`): a subsystem declares its
acquire/release or create/settle contract with ``register_pair`` and a
path-sensitive dataflow engine enforces it on every exit path,
resolving releases through helpers via the same package-wide Program.

Every entry point builds a package-wide call graph
(:mod:`~mxnet_tpu.lint.interproc`) and propagates blocking-ness,
host-sync, callback-fire, and holds-lock facts across resolved call
edges, so CC001/TS001/CC004 see through helper indirection and CC003
unions lock ordering across modules.

Suppress a finding with a trailing (or immediately preceding standalone)
comment ``# mxlint: disable=TS002`` (comma list, or ``disable=all``);
``# mxlint: disable-block=CC001`` on a compound statement (e.g. a
``with`` holding a transport lock by design) silences the rule for the
whole statement body — one audit point per critical section;
``# mxlint: skip-file`` skips a whole file.  Suppressions should carry a
rationale — they are audit points, not escape hatches.

Accepted findings live in a baseline ledger
(``ci/mxlint_baseline.json``; see :mod:`~mxnet_tpu.lint.baseline`):
``--baseline`` runs fail only on findings NOT in the ledger, so new
rules land without a zero-findings flag day and the ratchet only
tightens.

The static analyzer is complemented by a *runtime* trace guard
(``MXNET_TRACE_GUARD=warn|raise``, see ``mxnet_tpu.dispatch``) that
catches the host syncs static analysis cannot prove, e.g. through
aliases, getattr indirection, or dynamically-built callables.

This package is stdlib-only (ast + tokenize): linting never imports jax
or initializes a backend.
"""
from __future__ import annotations

from .core import (  # noqa: F401
    RULES,
    Finding,
    LintError,
    Rule,
    Severity,
    format_json,
    format_text,
    lint_file,
    lint_paths,
    lint_source,
    register_program_rule,
    register_rule,
)
from . import rules as _rules  # noqa: F401  (registers the rule set)
from . import lifecycle as _lifecycle  # noqa: F401  (registers RL rules)
from . import races as _races  # noqa: F401  (registers RC rules)
from .baseline import (  # noqa: F401
    compare,
    load_baseline,
    write_baseline,
)
from .interproc import Program  # noqa: F401

__all__ = ["RULES", "Finding", "LintError", "Rule", "Severity",
           "Program", "compare", "format_json", "format_text",
           "lint_file", "lint_paths", "lint_source", "load_baseline",
           "register_program_rule", "register_rule", "write_baseline"]
