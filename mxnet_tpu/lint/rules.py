"""mxlint builtin rules: trace-safety (TS001–TS006) and concurrency
(CC001–CC002).

Traced-region model
-------------------
A function body is *traced* when JAX runs it once to build a graph and
then replays the compiled artifact without re-running the Python:

* ``hybrid_forward`` methods (captured by gluon's ``_CachedOp`` as one
  pure jax function);
* functions decorated with (or passed to) a tracing entry point:
  ``jax.jit`` / ``dispatch.TrackedJit`` / ``eval_shape`` / ``grad`` /
  ``value_and_grad`` / ``vmap`` / ``pmap`` / ``shard_map`` / ``remat`` /
  ``lax.scan`` / ``while_loop`` / ``fori_loop`` / ``cond`` bodies;
* functions registered as framework ops (``ops.registry.register`` /
  ``OpDef``) — the registry jits every op impl;
* any ``def`` nested inside a traced function.

Matching is by terminal attribute name (``jax.jit`` and ``jit`` both
match), which trades a sliver of precision for zero-import analysis.

Taint model (TS001/TS004)
-------------------------
Inside a traced function, positional parameters without defaults (minus
``self``/``cls``/``F``) are assumed tracer-valued; taint propagates
through assignments.  Static accessors (``.shape``/``.ndim``/``.dtype``/
``.size``, ``len()``, ``isinstance()``, ``is None``) *kill* taint — those
are known at trace time and safe to branch on.
"""
from __future__ import annotations

import ast
import os

from .core import Severity, register_program_rule, register_rule

__all__ = ["ModuleContext"]

# tracing entry points, matched on the terminal name of the callee
TRACE_SINKS = frozenset({
    "jit", "TrackedJit", "eval_shape", "grad", "value_and_grad", "vmap",
    "pmap", "shard_map", "remat", "scan", "while_loop", "fori_loop",
    "cond", "switch", "custom_vjp", "custom_jvp",
})
# op-registry sinks: the registry jits every registered impl
REGISTRY_SINKS = frozenset({"OpDef", "register"})
TRACED_DEF_NAMES = frozenset({"hybrid_forward"})

# attribute reads that are static at trace time (kill taint)
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "stype",
                          "context", "ctx", "aval", "weak_type"})
# calls whose result is not a tracer even on tracer input (or that only
# inspect static structure)
UNTAINT_CALLS = frozenset({"len", "isinstance", "issubclass", "type",
                           "hasattr", "getattr", "callable", "id",
                           "repr", "str", "format"})

# host-sync method names (NDArray / jax.Array surface)
HOST_SYNC_METHODS = frozenset({"asnumpy", "asscalar", "item", "tolist",
                               "wait_to_read", "block_until_ready"})
# numpy entry points that force a concrete host value from their argument
NUMPY_SYNC_FUNCS = frozenset({"asarray", "array", "copy", "save",
                              "savez", "allclose", "array_equal"})

# container mutators whose effect escapes the trace when the receiver is
# not function-local
MUTATOR_METHODS = frozenset({"append", "extend", "insert", "add",
                             "update", "pop", "remove", "clear", "write",
                             "setdefault", "discard", "popitem",
                             "appendleft"})

# blocking primitives for CC001 (terminal attribute names)
BLOCKING_ATTRS = frozenset({"recv", "recvfrom", "recv_into", "accept",
                            "sendall", "connect", "create_connection",
                            "select", "poll"})
TIME_BLOCKING = frozenset({"sleep"})

# Future-settle surface (CC004): settling a concurrent.futures-style
# future runs its done-callbacks INLINE in the settling thread — doing it
# under a lock runs arbitrary user code inside the critical section
SETTLE_CALLS = frozenset({"set_result", "set_exception"})
# user-callback naming convention (on_token / on_batch / _on_scale_up)
CALLBACK_PREFIXES = ("on_", "_on_")

# receiver-name tokens that mark a `.wait()` as an event wait (CC005):
# an unbounded Event.wait() inside a supervisor tick never times out
EVENTISH_TOKENS = frozenset({"evt", "event", "stop", "done", "ready",
                             "quit", "shutdown", "flag"})

# method names too generic for package-unique attribute resolution —
# `q.get()` must never resolve to some random class's blocking `get`
GENERIC_METHOD_NAMES = frozenset({
    "get", "put", "set", "add", "pop", "run", "start", "stop", "close",
    "open", "read", "write", "send", "join", "wait", "acquire",
    "release", "append", "extend", "update", "clear", "next", "flush",
    "copy", "items", "keys", "values", "submit", "result", "cancel",
    "done", "count", "index", "sort", "reverse", "insert", "remove",
    "encode", "decode", "strip", "split", "format", "mean", "sum",
    "tolist", "step", "reset", "call", "apply", "emit", "push", "pull",
})


def _terminal_name(node):
    """Rightmost identifier of a Name/Attribute chain, else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root_name(node):
    """Leftmost Name of an Attribute/Subscript chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _dotted(node):
    """Dotted path of a pure Name/Attribute chain ('np.random.rand'),
    else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _walk_skip_nested(node):
    """Walk a function body without descending into nested function /
    class definitions (those get their own analysis pass)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class ModuleContext:
    """Per-file analysis context shared by every rule: the AST, source
    lines, import aliases, the set of traced function defs, and the
    module-level function table used for one-level call resolution."""

    def __init__(self, tree, path, lines):
        self.tree = tree
        self.path = path
        self.lines = lines
        self.module_stem = os.path.splitext(os.path.basename(path))[0]
        #: set by interproc.Program when this module is part of a
        #: package-wide run; rules consult it for cross-function facts
        self.program = None
        self.numpy_aliases = set()      # names bound to the numpy module
        self.np_random_aliases = set()  # names bound to numpy.random
        self.random_aliases = set()     # names bound to stdlib random
        self.time_aliases = set()       # names bound to time
        self.threading_aliases = set()
        self.from_random_names = set()  # from random import <name>
        self.from_time_names = set()    # from time import sleep
        self.thread_ctor_names = set()  # from threading import Thread
        self.mod_aliases = {}           # local name -> module stem
        self.from_imports = {}          # local name -> (mod stem, orig)
        self._collect_imports()
        self.functions = [n for n in ast.walk(tree)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))]
        self.func_by_name = {}
        for fn in self.functions:
            self.func_by_name.setdefault(fn.name, []).append(fn)
        self._parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.class_methods = {}         # class name -> {method: def}
        self.class_of = {}              # id(def) -> class name
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                methods = self.class_methods.setdefault(node.name, {})
                for ch in node.body:
                    if isinstance(ch, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        methods[ch.name] = ch
                        self.class_of[id(ch)] = node.name
        self.module_globals = set()     # names assigned at module level
        for st in tree.body:
            tgts = ()
            if isinstance(st, ast.Assign):
                tgts = st.targets
            elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
                tgts = (st.target,)
            for tgt in tgts:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name) and isinstance(
                            n.ctx, ast.Store):
                        self.module_globals.add(n.id)
        self.traced = self._find_traced()
        self._locals_cache = {}

    # -- imports ----------------------------------------------------------
    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    mod = alias.name
                    self.mod_aliases[name] = mod.split(".")[-1] \
                        if alias.asname else mod.split(".")[0]
                    if mod in ("numpy", "jax.numpy"):
                        self.numpy_aliases.add(name)
                    elif mod in ("numpy.random",):
                        self.np_random_aliases.add(alias.asname or "numpy")
                    elif mod == "random":
                        self.random_aliases.add(name)
                    elif mod == "time":
                        self.time_aliases.add(name)
                    elif mod == "threading":
                        self.threading_aliases.add(name)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    name = alias.asname or alias.name
                    if mod:
                        self.from_imports[name] = (mod.split(".")[-1],
                                                   alias.name)
                    elif node.level:  # from . import sibling
                        self.mod_aliases[name] = alias.name.split(".")[-1]
                    if mod == "numpy" and alias.name == "random":
                        self.np_random_aliases.add(name)
                    elif mod == "random":
                        self.from_random_names.add(name)
                    elif mod == "time" and alias.name in TIME_BLOCKING:
                        self.from_time_names.add(name)
                    elif mod == "threading" and alias.name == "Thread":
                        self.thread_ctor_names.add(name)

    # -- traced-function discovery ---------------------------------------
    def _decorator_traced(self, fn):
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = _terminal_name(target)
            if name in TRACE_SINKS or name in REGISTRY_SINKS:
                return True
            # functools.partial(jax.jit, ...) style decorators
            if name == "partial" and isinstance(dec, ast.Call) and dec.args:
                if _terminal_name(dec.args[0]) in TRACE_SINKS:
                    return True
        return False

    def _enclosing_fn(self, node):
        p = self._parents.get(node)
        while p is not None and not isinstance(
                p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            p = self._parents.get(p)
        return p

    def _is_within(self, node, ancestor):
        p = self._parents.get(node)
        while p is not None:
            if p is ancestor:
                return True
            p = self._parents.get(p)
        return False

    def _find_traced(self):
        traced = set()
        for fn in self.functions:
            if fn.name in TRACED_DEF_NAMES or self._decorator_traced(fn):
                traced.add(fn)

        def mark_name_args(call, scope):
            for arg in call.args:
                if isinstance(arg, ast.Name):
                    cands = self.func_by_name.get(arg.id, ())
                    # scope-aware resolution: `jit(call)` inside a
                    # factory refers to the nested `call`, not an
                    # unrelated same-named method elsewhere in the module
                    if scope is not None:
                        nested = [fd for fd in cands
                                  if self._is_within(fd, scope)]
                        cands = nested or cands
                    traced.update(cands)
                elif isinstance(arg, ast.Call):
                    # one nesting level: jit(shard_map(step, ...))
                    mark_name_args(arg, scope)

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name in TRACE_SINKS or name in REGISTRY_SINKS:
                    mark_name_args(node, self._enclosing_fn(node))
        # closure: defs nested inside a traced def are traced too
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn in traced:
                    continue
                p = self._parents.get(fn)
                while p is not None:
                    if isinstance(p, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and p in traced:
                        traced.add(fn)
                        changed = True
                        break
                    p = self._parents.get(p)
        return traced

    # -- per-function facts ----------------------------------------------
    def params_of(self, fn):
        a = fn.args
        names = [x.arg for x in
                 getattr(a, "posonlyargs", []) + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def assigned_in(self, fn):
        """Names bound inside ``fn``'s own body (nested defs excluded):
        every Name in Store context, plus nested def/class/import names."""
        got = self._locals_cache.get(fn)
        if got is not None:
            return got
        names = set()
        for n in _walk_skip_nested(fn):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                names.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.add(n.name)
            elif isinstance(n, (ast.Import, ast.ImportFrom)):
                for alias in n.names:
                    names.add(alias.asname or alias.name.split(".")[0])
            elif isinstance(n, ast.ExceptHandler) and n.name:
                names.add(n.name)
        self._locals_cache[fn] = names
        return names

    def _static_params(self, fn):
        """Param names declared static in a tracing decorator —
        ``static_argnums``/``nondiff_argnums``/``static_argnames`` on
        ``@jit(...)`` / ``@partial(jax.custom_vjp, ...)`` — those stay
        concrete Python values inside the trace."""
        pos = getattr(fn.args, "posonlyargs", []) + fn.args.args
        names = set()
        for dec in fn.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            for kw in dec.keywords:
                if kw.arg in ("static_argnums", "nondiff_argnums"):
                    elts = kw.value.elts if isinstance(
                        kw.value, (ast.Tuple, ast.List)) else [kw.value]
                    for el in elts:
                        if isinstance(el, ast.Constant) and isinstance(
                                el.value, int) and el.value < len(pos):
                            names.add(pos[el.value].arg)
                elif kw.arg == "static_argnames":
                    elts = kw.value.elts if isinstance(
                        kw.value, (ast.Tuple, ast.List)) else [kw.value]
                    for el in elts:
                        if isinstance(el, ast.Constant) and isinstance(
                                el.value, str):
                            names.add(el.value)
        return names

    def tainted_names(self, fn):
        """Tracer-tainted names in a traced fn: positional params without
        defaults (minus self/cls/F and decorator-declared static params),
        propagated through assignments in source order (one forward
        pass)."""
        a = fn.args
        pos = getattr(a, "posonlyargs", []) + a.args
        n_default = len(a.defaults)
        no_default = pos[:len(pos) - n_default] if n_default else pos
        tainted = {x.arg for x in no_default} - {"self", "cls", "F"}
        tainted -= self._static_params(fn)
        if a.vararg:
            tainted.add(a.vararg.arg)
        stmts = sorted(
            (n for n in _walk_skip_nested(fn)
             if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                               ast.For, ast.NamedExpr))),
            key=lambda n: (n.lineno, n.col_offset))
        for st in stmts:
            if isinstance(st, ast.For):
                if self.expr_tainted(st.iter, tainted):
                    for t in ast.walk(st.target):
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
                continue
            value = st.value
            if value is None:
                continue
            is_tainted = self.expr_tainted(value, tainted)
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            for tgt in targets:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Name) and isinstance(
                            t.ctx, ast.Store):
                        if is_tainted:
                            tainted.add(t.id)
                        else:
                            tainted.discard(t.id)
        return tainted

    def expr_tainted(self, node, tainted):
        """Could ``node`` evaluate to a tracer, given tainted names?
        Static accessors and shape-introspection calls kill taint."""
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value, tainted)
        if isinstance(node, ast.Call):
            fname = _terminal_name(node.func)
            if fname in UNTAINT_CALLS or fname in ("int", "float", "bool"):
                return False
            args = list(node.args) + [kw.value for kw in node.keywords]
            if self.expr_tainted(node.func, tainted):
                return True
            return any(self.expr_tainted(x, tainted) for x in args)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return any(self.expr_tainted(x, tainted)
                       for x in [node.left] + node.comparators)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension)):
                if isinstance(child, ast.comprehension):
                    if self.expr_tainted(child.iter, tainted):
                        return True
                elif self.expr_tainted(child, tainted):
                    return True
        return False

    def traced_defs(self):
        return [fn for fn in self.functions if fn in self.traced]

    # -- module-level blocking-call map (CC001) ---------------------------
    def is_blocking_call(self, call):
        """Direct blocking primitive?  (socket recv/accept/sendall/...,
        time.sleep, Thread/Process.join — str.join is screened out by its
        single non-numeric argument.)"""
        name = _terminal_name(call.func)
        if name == "poll":
            # select poller.poll(timeout) blocks; Popen.poll() is an
            # instant status probe — distinguish by argument presence
            # and a poller-ish receiver name
            recv = _terminal_name(call.func.value) \
                if isinstance(call.func, ast.Attribute) else None
            return bool(call.args) or bool(
                recv and ("poll" in recv.lower() or "sel" in recv.lower()))
        if name in BLOCKING_ATTRS:
            return True
        if name == "join":
            # thread.join() / thread.join(0.05) / join(timeout=...) are
            # blocking; " ".join(parts) takes one non-numeric positional
            if any(kw.arg == "timeout" for kw in call.keywords):
                return True
            if not call.args and not call.keywords:
                return True
            return (len(call.args) == 1
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, (int, float)))
        if name in TIME_BLOCKING:
            dotted = _dotted(call.func)
            if dotted and "." in dotted:
                return dotted.split(".")[0] in self.time_aliases
            return name in self.from_time_names
        if name == "wait":
            # Event.wait()/Condition.wait(): only flag the zero-arg form
            # explicitly given a timeout=None default — too ambiguous
            # otherwise (Condition.wait REQUIRES the lock held)
            return False
        return False

    def blocking_functions(self):
        """Names of module-level (or method) defs whose bodies contain a
        direct blocking call — one level of interprocedural resolution so
        ``_send_msg``-style wrappers are still caught under a lock."""
        out = set()
        for fn in self.functions:
            for n in _walk_skip_nested(fn):
                if isinstance(n, ast.Call) and self.is_blocking_call(n):
                    out.add(fn.name)
                    break
        return out


# ===========================================================================
# Trace-safety rules
# ===========================================================================
@register_rule("TS001", Severity.ERROR,
               "host sync inside traced code")
def check_host_sync(ctx):
    """``.asnumpy()``/``.item()``/``float()``/``np.asarray`` inside a
    traced body either raises at trace time (tracer input) or — worse —
    silently executes at *trace* time on a closure-captured concrete
    array, baking a stale constant into every future execution."""
    for fn in ctx.traced_defs():
        tainted = ctx.tainted_names(fn)
        for node in _walk_skip_nested(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and name in HOST_SYNC_METHODS:
                yield (node, None,
                       ".%s() is a device->host sync inside traced "
                       "function %r: it runs once at trace time (baking "
                       "a constant) or raises on a tracer. Return the "
                       "value and sync outside the traced region."
                       % (name, fn.name))
            elif isinstance(node.func, ast.Name) \
                    and name in ("float", "int", "bool") and node.args \
                    and ctx.expr_tainted(node.args[0], tainted):
                yield (node, None,
                       "%s() on a traced value inside %r forces "
                       "concretization (ConcretizationError or a baked "
                       "constant). Keep it as an array, or branch on "
                       "static .shape/.dtype." % (name, fn.name))
            elif isinstance(node.func, ast.Attribute) \
                    and name in NUMPY_SYNC_FUNCS:
                dotted = _dotted(node.func)
                root = dotted.split(".")[0] if dotted else None
                if root in ctx.numpy_aliases and node.args and \
                        ctx.expr_tainted(node.args[0], tainted) and \
                        root not in ("jnp",):
                    yield (node, None,
                           "%s(<traced value>) inside %r pulls the "
                           "array to host numpy at trace time. Use "
                           "jax.numpy on device, or move the host "
                           "conversion outside the traced region."
                           % (dotted, fn.name))
            elif isinstance(node.func, ast.Attribute) \
                    and name == "device_get":
                yield (node, None,
                       "jax.device_get inside traced function %r is a "
                       "host transfer at trace time." % fn.name)
            elif name is not None and ctx.program is not None:
                # inter-procedural: a helper that syncs taints the
                # traced caller (the helper itself is only flagged when
                # it is traced in its own right)
                hit = ctx.program.host_sync_callee(ctx, fn, node)
                if hit is not None:
                    yield (node, None,
                           "%r performs a device->host sync (via %s) "
                           "and is called inside traced function %r: "
                           "the sync runs at trace time (baked "
                           "constant) or raises on a tracer. Sync "
                           "outside the traced region."
                           % (name, hit, fn.name))


@register_rule("TS002", Severity.ERROR,
               "trace-time side effect in a traced body")
def check_side_effects(ctx):
    """A traced body runs ONCE per shape signature; attribute mutation,
    ``print``, clocks, and container appends to enclosing state happen at
    trace time only — silently absent from the compiled program (and
    re-run on every recompile)."""
    for fn in ctx.traced_defs():
        local = ctx.assigned_in(fn)
        for node in _walk_skip_nested(fn):
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if isinstance(node.func, ast.Name) and name == "print":
                    yield (node, None,
                           "print() inside traced function %r executes "
                           "at trace time only (once per compilation, "
                           "never per step). Use jax.debug.print, or "
                           "log outside the traced region." % fn.name)
                elif isinstance(node.func, ast.Attribute) and \
                        name in ("time", "perf_counter", "monotonic",
                                 "process_time"):
                    dotted = _dotted(node.func)
                    if dotted and dotted.split(".")[0] in ctx.time_aliases:
                        yield (node, None,
                               "%s() inside traced function %r is "
                               "evaluated once at trace time — the "
                               "compiled step reuses that stale "
                               "timestamp forever. Time the call site "
                               "outside the trace." % (dotted, fn.name))
                elif isinstance(node.func, ast.Attribute) and \
                        name in MUTATOR_METHODS:
                    root = _root_name(node.func.value)
                    if root is not None and root not in local:
                        yield (node, None,
                               "mutating %r (closure/global) via .%s() "
                               "inside traced function %r is a trace-"
                               "time side effect: it fires once per "
                               "compilation, not once per call."
                               % (root, name, fn.name))
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        root = _root_name(tgt)
                        if root is not None and root not in local:
                            yield (tgt, None,
                                   "writing %s on non-local %r inside "
                                   "traced function %r is a trace-time "
                                   "side effect (runs once per "
                                   "compilation; invisible to the "
                                   "compiled program). Return the value "
                                   "instead."
                                   % ("an attribute" if isinstance(
                                       tgt, ast.Attribute)
                                      else "an item", root, fn.name))
            elif isinstance(node, ast.Global):
                yield (node, None,
                       "'global' inside traced function %r: rebinding "
                       "module state at trace time is a side effect the "
                       "compiled program never sees." % fn.name)


@register_rule("TS003", Severity.ERROR,
               "untracked randomness inside traced code")
def check_randomness(ctx):
    """``np.random``/stdlib ``random`` inside a traced body draws ONE
    sample at trace time and bakes it in — every compiled call reuses the
    same 'random' numbers.  ``mxnet_tpu.random`` threads a key through
    the trace so compiled programs stay stochastic AND reproducible."""
    for fn in ctx.traced_defs():
        for node in _walk_skip_nested(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            parts = dotted.split(".")
            if len(parts) >= 2 and parts[0] in ctx.numpy_aliases \
                    and parts[1] == "random":
                yield (node, None,
                       "%s inside traced function %r draws at trace "
                       "time: the compiled program replays one frozen "
                       "sample. Use mxnet_tpu.random (key-threaded) "
                       "instead." % (dotted, fn.name))
            elif parts[0] in ctx.np_random_aliases and len(parts) >= 2:
                yield (node, None,
                       "%s inside traced function %r draws at trace "
                       "time (frozen sample). Use mxnet_tpu.random."
                       % (dotted, fn.name))
            elif parts[0] in ctx.random_aliases and len(parts) == 2:
                yield (node, None,
                       "stdlib %s inside traced function %r draws at "
                       "trace time (frozen sample) and is invisible to "
                       "mxnet_tpu.random.seed(). Use mxnet_tpu.random."
                       % (dotted, fn.name))
            elif len(parts) == 1 and parts[0] in ctx.from_random_names:
                yield (node, None,
                       "stdlib random.%s inside traced function %r "
                       "draws at trace time (frozen sample). Use "
                       "mxnet_tpu.random." % (parts[0], fn.name))


@register_rule("TS004", Severity.WARNING,
               "Python control flow on a traced value")
def check_tracer_branch(ctx):
    """``if``/``while`` on a tracer-valued expression raises
    ConcretizationError under jit — or, via shape-dependent paths,
    silently recompiles per value.  Branch on static ``.shape``/
    ``.dtype``, or use ``F.where`` / ``lax.cond``."""
    for fn in ctx.traced_defs():
        tainted = ctx.tainted_names(fn)
        if not tainted:
            continue
        for node in _walk_skip_nested(fn):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            else:
                continue
            if ctx.expr_tainted(test, tainted):
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression",
                        ast.Assert: "assert"}[type(node)]
                yield (node, None,
                       "%s on a traced value inside %r: under jit this "
                       "raises ConcretizationError (or forces a "
                       "recompile per concrete value). Branch on static "
                       ".shape/.dtype, or use F.where / lax.cond."
                       % (kind, fn.name))


@register_rule("TS005", Severity.ERROR,
               "use-after-donate of a buffer")
def check_use_after_donate(ctx):
    """An argument passed through a donating jit call (``donate_argnums``
    / ``TrackedJit(..., donate_argnums=...)``) is consumed by XLA: the
    pre-call buffer is deleted (in-place HBM reuse).  Reading the same
    variable afterwards raises 'buffer was deleted' — or worse, observes
    a stale copy if donation was declined."""
    for scope in [ctx.tree] + ctx.functions:
        walk = _walk_skip_nested(scope) if scope is not ctx.tree else (
            n for n in _walk_skip_nested(scope)
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
        nodes = sorted(
            (n for n in walk
             if isinstance(n, (ast.Assign, ast.Call, ast.Name))),
            key=lambda n: (n.lineno, n.col_offset))
        donating = {}     # local name -> donated positions
        donated = {}      # var name -> (line of donating call)
        assigns = {}      # var name -> [assignment lines]

        def donate_positions(call):
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    if isinstance(kw.value, (ast.Tuple, ast.List)):
                        out = []
                        for el in kw.value.elts:
                            if isinstance(el, ast.Constant) and \
                                    isinstance(el.value, int):
                                out.append(el.value)
                        return tuple(out)
                    if isinstance(kw.value, ast.Constant) and \
                            isinstance(kw.value.value, int):
                        return (kw.value.value,)
            return None

        def is_jit_ctor(call):
            return _terminal_name(call.func) in ("jit", "TrackedJit")

        handled_calls = set()

        def process_call(n):
            positions = None
            if isinstance(n.func, ast.Name) and n.func.id in donating:
                positions = donating[n.func.id]
            elif isinstance(n.func, ast.Call) and is_jit_ctor(n.func):
                # jax.jit(f, donate_argnums=(0,))(x) inline call
                positions = donate_positions(n.func)
            if positions:
                for pos in positions:
                    if pos < len(n.args) and isinstance(
                            n.args[pos], ast.Name):
                        donated.setdefault(n.args[pos].id, n.lineno)

        for n in nodes:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                positions = donate_positions(n.value) \
                    if is_jit_ctor(n.value) else None
                # evaluation order: the value call runs (donating its
                # args) BEFORE the target is rebound, so `w = fast(w)`
                # both donates and then refreshes `w`
                if positions is None:
                    process_call(n.value)
                handled_calls.add(id(n.value))
                for tgt in n.targets:
                    if isinstance(tgt, ast.Name):
                        assigns.setdefault(tgt.id, []).append(n.lineno)
                        if positions:
                            donating[tgt.id] = positions
                        else:
                            donating.pop(tgt.id, None)
                        donated.pop(tgt.id, None)
            elif isinstance(n, ast.Assign):
                for tgt in n.targets:
                    for t in ast.walk(tgt):
                        if isinstance(t, ast.Name):
                            assigns.setdefault(t.id, []).append(n.lineno)
                            donated.pop(t.id, None)
            elif isinstance(n, ast.Call):
                if id(n) not in handled_calls:
                    process_call(n)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                at = donated.get(n.id)
                if at is not None and n.lineno > at:
                    yield (n, None,
                           "%r was donated to a compiled call at line "
                           "%d (donate_argnums): its device buffer is "
                           "deleted after the call. Use the call's "
                           "RETURN value, .copy() before donating, or "
                           "dispatch.no_donation()." % (n.id, at))
                    donated.pop(n.id, None)  # one finding per donation


# reductions whose result can be exactly 0 (empty/masked/underflowed
# input) — dividing by one, or taking log/sqrt of one, is the classic
# silent-NaN factory inside compiled op code
REDUCTION_NAMES = frozenset({"sum", "mean", "prod", "norm", "var", "std",
                             "min", "max", "amin", "amax",
                             "count_nonzero"})
# math entry points that are non-finite at 0 (or negative) input
UNSAFE_AT_ZERO = frozenset({"log", "log2", "log10", "sqrt", "rsqrt",
                            "reciprocal"})


@register_rule("TS006", Severity.WARNING,
               "unguarded division/log on a traced reduction")
def check_unguarded_math(ctx):
    """Dividing by — or taking ``log``/``sqrt`` of — the raw result of a
    reduction (``sum``/``mean``/``norm``/``max``/…) over traced data is
    how NaNs are born inside compiled ops: an all-masked batch, an
    underflowed bf16 accumulation, or an empty slice makes the reduction
    exactly 0, the division mints inf/NaN, and XLA happily propagates it
    into the parameters (no exception is ever raised under jit).  Guard
    the denominator/argument: ``maximum(d, eps)``, ``clip``, ``d + eps``,
    ``where(d != 0, d, 1)``, or ``nan_to_num`` — any wrapping guard
    silences this rule."""
    for fn in ctx.traced_defs():
        tainted = ctx.tainted_names(fn)
        if not tainted:
            continue

        def reduction_call(node):
            if not isinstance(node, ast.Call):
                return False
            if _terminal_name(node.func) not in REDUCTION_NAMES:
                return False
            if isinstance(node.func, ast.Attribute) and \
                    ctx.expr_tainted(node.func.value, tainted):
                return True  # x.sum() method form
            return any(ctx.expr_tainted(a, tainted) for a in node.args)

        # one ordered pass: track names currently bound to a BARE
        # reduction result (rebinding to anything else — including a
        # guarded expression — clears the name)
        red_names = set()

        def risky(node):
            return reduction_call(node) or (
                isinstance(node, ast.Name) and node.id in red_names)

        for node in sorted(
                _walk_skip_nested(fn),
                key=lambda n: (getattr(n, "lineno", 0),
                               getattr(n, "col_offset", 0))):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div) \
                    and risky(node.right):
                yield (node, None,
                       "dividing by a raw reduction result inside traced "
                       "function %r: a fully-masked/empty/underflowed "
                       "input makes it exactly 0 and the compiled step "
                       "mints inf/NaN silently. Guard the denominator "
                       "(maximum(d, eps), d + eps, where(d != 0, d, 1))."
                       % fn.name)
            elif isinstance(node, ast.Call) and \
                    _terminal_name(node.func) in UNSAFE_AT_ZERO and \
                    node.args and risky(node.args[0]):
                yield (node, None,
                       "%s() of a raw reduction result inside traced "
                       "function %r is non-finite at 0: an empty or "
                       "fully-masked input NaNs the compiled step "
                       "silently. Clamp first (maximum(x, eps) / clip)."
                       % (_terminal_name(node.func), fn.name))
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)) and node.value is not None:
                is_red = reduction_call(node.value) or (
                    isinstance(node.value, ast.Name)
                    and node.value.id in red_names)
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    for t in ast.walk(tgt):
                        if isinstance(t, ast.Name) and isinstance(
                                t.ctx, ast.Store):
                            if is_red:
                                red_names.add(t.id)
                            else:
                                red_names.discard(t.id)


# ===========================================================================
# Concurrency rules
# ===========================================================================
def _lockish(expr):
    """Is this `with` context expression a lock?  Name/Attribute chains
    whose terminal identifier contains 'lock'/'mutex', or whose
    snake_case tokens name a condition variable or semaphore (entering
    ``with cv:`` acquires the underlying lock all the same)."""
    name = _terminal_name(expr)
    if name is None:
        return False
    low = name.lower()
    if "lock" in low or "mutex" in low:
        return True
    tokens = set(low.split("_"))
    return bool(tokens & {"cv", "cond", "condition", "sem", "semaphore"})


def _lock_exprs(with_node):
    """The lockish context expressions of a ``with`` statement (the
    callee for ``with make_lock():`` call forms)."""
    out = []
    for item in with_node.items:
        expr = item.context_expr
        tgt = expr.func if isinstance(expr, ast.Call) else expr
        if _lockish(tgt):
            out.append(tgt)
    return out


def _thread_creations(ctx):
    """Yield ``(call_node, daemon, target_expr)`` for every
    ``threading.Thread(...)`` construction in the module.  ``daemon`` is
    True when the ctor passes ``daemon=True`` or the bound name later
    gets ``.daemon = True`` assigned."""
    daemon_assigned = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "daemon":
                    root = _terminal_name(tgt.value)
                    if root:
                        daemon_assigned.add(root)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name != "Thread":
            continue
        dotted = _dotted(node.func)
        if dotted and "." in dotted:
            if dotted.split(".")[0] not in ctx.threading_aliases:
                continue
        elif name not in ctx.thread_ctor_names:
            continue
        daemon = any(kw.arg == "daemon"
                     and isinstance(kw.value, ast.Constant)
                     and kw.value.value is True for kw in node.keywords)
        if not daemon:
            parent = ctx._parents.get(node)
            if isinstance(parent, ast.Assign):
                for tgt in parent.targets:
                    if _terminal_name(tgt) in daemon_assigned:
                        daemon = True
        target = next((kw.value for kw in node.keywords
                       if kw.arg == "target"), None)
        yield node, daemon, target


@register_rule("CC001", Severity.ERROR,
               "lock held across a blocking call")
def check_lock_blocking(ctx):
    """Holding a lock across a blocking call (socket recv/sendall,
    thread join, sleep) serializes every other thread on I/O latency —
    and deadlocks outright if the blocked peer needs the same lock.
    Move the blocking call outside the critical section (stage the data
    under the lock, send after release).

    With the inter-procedural pass (always on), a call whose resolved
    callee *transitively* blocks — a helper one or more hops away that
    sleeps or does socket I/O — taints the call site under the lock; the
    message carries the witness chain down to the blocking primitive."""
    blocking_fns = ctx.blocking_functions()
    prog = ctx.program
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        lock_exprs = _lock_exprs(node)
        if not lock_exprs:
            continue
        lock_label = next((_terminal_name(e) for e in lock_exprs
                           if _terminal_name(e)), "lock")
        caller = ctx._enclosing_fn(node)
        for inner in ast.walk(node):
            if inner is node or not isinstance(inner, ast.Call):
                continue
            if ctx.is_blocking_call(inner):
                yield (inner, None,
                       "blocking call %r while holding %r: every other "
                       "thread contending for the lock stalls on this "
                       "I/O (deadlock if the peer needs the lock). "
                       "Stage under the lock, block after release."
                       % (_terminal_name(inner.func) or "call",
                          lock_label))
                continue
            callee = _terminal_name(inner.func)
            if callee is None:
                continue
            if prog is not None:
                hit = prog.blocking_callee(ctx, caller, inner)
                if hit is not None:
                    yield (inner, None,
                           "%r blocks while holding %r (via %s): the "
                           "critical section waits on the I/O. Stage "
                           "the payload under the lock and call %r "
                           "after release." % (callee, lock_label,
                                               hit, callee))
            elif callee in blocking_fns:
                yield (inner, None,
                       "%r (which performs blocking I/O) called "
                       "while holding %r: the critical section "
                       "waits on the network. Stage the payload "
                       "under the lock and call %r after release."
                       % (callee, lock_label, callee))


@register_rule("CC002", Severity.ERROR,
               "non-daemon thread without a join path")
def check_thread_lifecycle(ctx):
    """A non-daemon thread with no ``join()`` keeps the process alive
    after main exits (hung CI, zombie workers on preemption).  Either
    mark it ``daemon=True`` (ok to die with the process) or join it on
    every exit path."""
    joined_roots = set()
    daemon_assigned_roots = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                _terminal_name(node.func) == "join" and \
                isinstance(node.func, ast.Attribute):
            root = _terminal_name(node.func.value)
            if root:
                joined_roots.add(root)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "daemon":
                    root = _terminal_name(tgt.value)
                    if root:
                        daemon_assigned_roots.add(root)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        dotted = _dotted(node.func)
        is_thread = False
        if name == "Thread":
            if dotted and "." in dotted:
                is_thread = dotted.split(".")[0] in ctx.threading_aliases
            else:
                is_thread = name in ctx.thread_ctor_names
        if not is_thread:
            continue
        daemon_true = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True for kw in node.keywords)
        if daemon_true:
            continue
        # find the variable (or attribute) the thread is bound to
        parent = ctx._parents.get(node)
        target = None
        if isinstance(parent, ast.Assign):
            for tgt in parent.targets:
                target = _terminal_name(tgt)
        elif isinstance(parent, ast.Attribute):
            # Thread(...).start() — anonymous, can never be joined
            target = None
        if target and (target in joined_roots
                       or target in daemon_assigned_roots):
            continue
        yield (node, None,
               "non-daemon Thread%s has no join path in this module: "
               "the process cannot exit while it runs (hung shutdown / "
               "zombie worker on preemption). Pass daemon=True or join "
               "it on every exit path."
               % (" bound to %r" % target if target else ""))


@register_program_rule("CC003", Severity.ERROR,
                       "lock-order inversion across the package")
def check_lock_order_cycles(program):
    """Two code paths that acquire the same pair of locks in opposite
    orders can deadlock the moment both run concurrently — each holds
    what the other needs.  The acquisition-order graph is unioned across
    the whole package (nested ``with`` blocks plus lock acquisitions
    reached through resolved calls), so an inversion between, say, a
    serving method and a fleet callback is caught even though no single
    function ever takes both locks.  Every cycle is reported once, with
    a witness path for each edge."""
    for cycle in program.lock_cycles():
        (a, b, path, line, _why) = cycle[0]
        edges = "; ".join(
            "%s -> %s at %s:%d (%s)" % (ea, eb, os.path.basename(ep),
                                        el, ew)
            for (ea, eb, ep, el, ew) in cycle)
        yield (path, line, 0,
               "lock-order cycle: %s. Threads taking these locks in "
               "opposite orders deadlock under contention; pick one "
               "global order and acquire in that order everywhere "
               "(docs/STATIC_ANALYSIS.md#lock-ordering)." % edges)


@register_rule("CC004", Severity.ERROR,
               "user callback or Future settled while holding a lock")
def check_callback_under_lock(ctx):
    """Settling a future (``set_result``/``set_exception``) runs its
    done-callbacks INLINE in the settling thread; firing a user callback
    (``on_token``/``on_batch``/...) hands arbitrary user code the
    thread.  Doing either while holding a lock means user code executes
    inside the critical section — it can block forever, or re-enter the
    server and deadlock on the very lock being held.  Stage the
    callback/settle under the lock, fire after release."""
    prog = ctx.program
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        lock_exprs = _lock_exprs(node)
        if not lock_exprs:
            continue
        lock_label = next((_terminal_name(e) for e in lock_exprs
                           if _terminal_name(e)), "lock")
        caller = ctx._enclosing_fn(node)
        for inner in ast.walk(node):
            if inner is node or not isinstance(inner, ast.Call):
                continue
            name = _terminal_name(inner.func)
            if name is None:
                continue
            if name in SETTLE_CALLS:
                yield (inner, None,
                       "%s() while holding %r: settling a future runs "
                       "its done-callbacks inline inside the critical "
                       "section (user code can block or re-enter and "
                       "deadlock). Collect the future under the lock, "
                       "settle after release." % (name, lock_label))
            elif name.startswith(CALLBACK_PREFIXES):
                yield (inner, None,
                       "user callback %s() fired while holding %r: "
                       "callbacks must fire OUTSIDE all locks (user "
                       "code can block, or call back into the server "
                       "and deadlock on %r). Stage the payload under "
                       "the lock, fire after release."
                       % (name, lock_label, lock_label))
            elif prog is not None:
                hit = prog.callback_callee(ctx, caller, inner)
                if hit is not None:
                    yield (inner, None,
                           "%r settles a future or fires a user "
                           "callback (via %s) while %r is held: user "
                           "code runs inside the critical section. "
                           "Move the call after release."
                           % (name, hit, lock_label))


@register_rule("CC005", Severity.WARNING,
               "unbounded blocking reachable from a daemon loop")
def check_daemon_loop_blocking(ctx):
    """Daemon supervisor ticks (heartbeat/control/monitor loops) must
    bound every wait: the loop body reaching raw socket I/O or an
    unbounded ``join()``/``Event.wait()`` means one wedged peer stalls
    the tick forever — heartbeats stop, TTLs expire, and the fleet
    declares a healthy process dead.  Route I/O through a
    timeout-bearing transport and give every wait a timeout."""
    prog = ctx.program
    if prog is None:
        return
    for node, daemon, target in _thread_creations(ctx):
        if not daemon or target is None:
            continue
        caller = ctx._enclosing_fn(node)
        for finfo in prog.resolve_callable(ctx, caller, target):
            why = finfo.io_blocking or finfo.unbounded
            if why is None:
                continue
            kind = ("raw socket I/O" if finfo.io_blocking
                    else "an unbounded wait")
            yield (node, None,
                   "daemon loop %r reaches %s (%s): a wedged peer "
                   "stalls this supervisor tick forever (missed "
                   "heartbeats read as process death). Bound every "
                   "wait and use a timeout-bearing transport."
                   % (finfo.qualname, kind, why))
            break


@register_rule("TS007", Severity.ERROR,
               "unhashable/unstable static arg to TrackedJit")
def check_static_arg_stability(ctx):
    """``static_argnums`` hashes the argument into the compile-cache
    key.  A dict/list/set there raises ``TypeError: unhashable`` at the
    first call — or, with a mutable *default* on the wrapped function,
    every call site that builds a fresh container forces a fresh trace:
    a recompile storm the flight recorder can only report after the
    fact.  Pass tuples / frozensets / scalars in static positions."""

    def static_positions(call):
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    return tuple(el.value for el in kw.value.elts
                                 if isinstance(el, ast.Constant)
                                 and isinstance(el.value, int))
                if isinstance(kw.value, ast.Constant) and isinstance(
                        kw.value.value, int):
                    return (kw.value.value,)
        return ()

    def is_mutable_literal(node):
        if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                             ast.ListComp, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("dict", "list", "set"))

    def is_jit_ctor(call):
        return _terminal_name(call.func) in ("jit", "TrackedJit")

    def check_fn_defaults(call, positions):
        """TrackedJit(f, static_argnums=(k,)) where f's k-th param
        carries a dict/list/set default."""
        if not call.args or not isinstance(call.args[0], ast.Name):
            return
        cands = ctx.func_by_name.get(call.args[0].id, ())
        if len(cands) != 1:
            if ctx.program is None:
                return
            infos = ctx.program.resolve_callable(ctx, None, call.args[0])
            if len(infos) != 1:
                return
            cands = (infos[0].node,)
        fn = cands[0]
        pos = getattr(fn.args, "posonlyargs", []) + fn.args.args
        n_default = len(fn.args.defaults)
        first_default = len(pos) - n_default
        for k in positions:
            if k >= len(pos):
                continue
            d = k - first_default
            if 0 <= d < n_default and is_mutable_literal(
                    fn.args.defaults[d]):
                yield (call, None,
                       "static arg %d of %r defaults to a dict/list/"
                       "set: unhashable in the compile-cache key "
                       "(TypeError at call time) and a fresh default "
                       "per call means a fresh trace per call — a "
                       "recompile storm. Use a tuple/frozenset/None "
                       "sentinel." % (k, call.args[0].id))

    for scope in [ctx.tree] + ctx.functions:
        walk = _walk_skip_nested(scope) if scope is not ctx.tree else (
            n for n in _walk_skip_nested(scope)
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
        nodes = sorted((n for n in walk
                        if isinstance(n, (ast.Assign, ast.Call))),
                       key=lambda n: (n.lineno, n.col_offset))
        static_of = {}  # local name -> static positions
        for n in nodes:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                call = n.value
                if is_jit_ctor(call):
                    positions = static_positions(call)
                    for hit in check_fn_defaults(call, positions):
                        yield hit
                    for tgt in n.targets:
                        if isinstance(tgt, ast.Name):
                            if positions:
                                static_of[tgt.id] = positions
                            else:
                                static_of.pop(tgt.id, None)
            elif isinstance(n, ast.Call):
                positions = None
                if isinstance(n.func, ast.Name) and \
                        n.func.id in static_of:
                    positions = static_of[n.func.id]
                elif isinstance(n.func, ast.Call) and is_jit_ctor(n.func):
                    positions = static_positions(n.func)
                    for hit in check_fn_defaults(n.func, positions):
                        yield hit
                if not positions:
                    continue
                for k in positions:
                    if k < len(n.args) and is_mutable_literal(n.args[k]):
                        yield (n.args[k], None,
                               "dict/list/set passed in static position "
                               "%d: static args are hashed into the "
                               "compile-cache key — this raises "
                               "TypeError (unhashable), and a fresh "
                               "container per call would retrace per "
                               "call. Pass a tuple/frozenset." % k)
