"""Standalone predict (serving) API.

Reference parity: ``src/c_api/c_predict_api.cc`` /
``include/mxnet/c_predict_api.h:1-283`` — the minimal inference ABI: load a
``prefix-symbol.json`` + ``prefix-####.params`` pair (written by
``model.save_checkpoint`` or Gluon ``HybridBlock.export``) in a fresh
process, bind for fixed input shapes, and run batched forward passes.

TPU-native: the whole graph lowers to ONE jit'd XLA module (inference only,
``grad_req='null'``); ``aot=True`` compiles at construction time
(``jax.jit(...).lower().compile()`` — the analogue of the reference's
bind-time ``GraphExecutor::Init``) so the first request pays no compile.

Both the pythonic :class:`Predictor` and the C-shaped ``MXPred*`` functions
(handle-based, mirroring the reference ABI one-to-one) are provided.
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from .context import Context, cpu
from .ndarray import NDArray
from .symbol import load_json as _sym_load_json

__all__ = ["Predictor", "MXPredCreate", "MXPredCreatePartialOut",
           "MXPredReshape", "MXPredGetOutputShape", "MXPredSetInput",
           "MXPredForward", "MXPredGetOutput", "MXPredFree"]


def _load_params(source):
    """Accept a params file path, raw bytes, or a {name: NDArray} dict;
    returns (arg_params, aux_params) with prefixes stripped."""
    if isinstance(source, dict):
        loaded = source
    elif isinstance(source, (bytes, bytearray, memoryview)):
        # straight from the in-memory buffer — the old NamedTemporaryFile
        # round-trip re-opened the file while the writing handle was still
        # open, which fails on platforms without shared-open semantics
        loaded = nd.load_frombuffer(bytes(source))
    else:
        loaded = nd.load(source)
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


class Predictor:
    """Inference-only executor over an exported symbol+params pair
    (reference ``MXPredCreate`` -> ``PredictorObj``).

    **pjit-sharded mode** (docs/SHARDED_SERVING.md): pass ``mesh=`` (a
    :class:`~mxnet_tpu.parallel.mesh.DeviceMesh`, e.g. one slice from
    :func:`~mxnet_tpu.parallel.mesh.mesh_slices`) plus ``rules=`` (a
    :class:`~mxnet_tpu.parallel.sharding.ShardingRules` or a list of
    ``(regex, PartitionSpec)`` pairs) and the bound weights are placed
    across the mesh's devices with NamedShardings; XLA/GSPMD propagates
    the activation shardings and inserts every collective.  Inputs stay
    host-staged (uncommitted — jit replicates them), so the compile
    cache keys are identical to the single-device path: a warmed
    sharded predictor never recompiles under load.  ``warm()`` /
    ``health_check()`` / ``clone()`` / ``reshape()`` work unchanged."""

    def __init__(self, symbol, params, ctx=None, input_shapes=None,
                 input_dtypes=None, output_names=None, aot=True,
                 mesh=None, rules=None):
        from .symbol import Symbol, load as sym_load
        if isinstance(symbol, Symbol):
            sym = symbol
        elif isinstance(symbol, str) and symbol.lstrip().startswith("{"):
            sym = _sym_load_json(symbol)
        else:
            sym = sym_load(symbol)
        if output_names is not None:
            outs = sym.list_outputs()
            picked = []
            for name in output_names:
                if name not in outs:
                    raise ValueError("output %r not found in %s"
                                     % (name, outs))
                picked.append(sym[outs.index(name)])
            from .symbol import Group
            sym = Group(picked)
        self._symbol = sym
        self._ctx = ctx or cpu()
        arg_params, aux_params = _load_params(params)
        input_shapes = dict(input_shapes or {})
        self._input_names = [n for n in sym.list_arguments()
                             if n not in arg_params]
        missing = [n for n in self._input_names if n not in input_shapes]
        if missing:
            # label inputs of training heads (SoftmaxOutput etc.) are
            # inert at inference: infer their shapes from the data inputs
            # and bind zeros (reference c_predict_api binds them too)
            try:
                inferred, _, _ = sym.infer_shape(**input_shapes)
                by_name = dict(zip(sym.list_arguments(), inferred))
            except Exception:
                by_name = {}
            still = []
            for n in missing:
                shp = by_name.get(n)
                if shp is not None and n.endswith("label"):
                    input_shapes[n] = shp
                else:
                    still.append(n)
            if still:
                raise ValueError("input_shapes must cover the data "
                                 "inputs; missing %s" % still)

        # sharded mode rebinds weights in place (_apply_sharding), so it
        # must own them: as_in_context returns the SAME NDArray when the
        # ctx already matches, and re-sharding a param shared with a
        # sibling replica would silently move that replica's weights
        # onto this replica's mesh slice
        def _own(arr):
            arr = arr.as_in_context(self._ctx)
            if mesh is None:
                return arr
            try:
                devs = arr.data.sharding.device_set
                if len(devs) > 1 and \
                        devs == set(mesh.mesh.devices.flat):
                    return arr      # already on this slice (clone path)
            except (AttributeError, TypeError):
                pass
            return arr.copy()

        args = {}
        for name in sym.list_arguments():
            if name in arg_params:
                args[name] = _own(arg_params[name])
            else:
                dt = (input_dtypes or {}).get(name, np.float32)
                args[name] = nd.zeros(input_shapes[name], dtype=dt,
                                      ctx=self._ctx)
        auxs = {}
        for name in sym.list_auxiliary_states():
            if name not in aux_params:
                raise ValueError("missing auxiliary state %r in params"
                                 % name)
            auxs[name] = _own(aux_params[name])

        self._input_dtypes = dict(input_dtypes or {})
        self._executor = sym.bind(ctx=self._ctx, args=args, grad_req="null",
                                  aux_states=auxs)
        self._mesh = mesh
        self._rules = rules
        if mesh is not None:
            self._apply_sharding()
        self.outputs = None
        if aot:
            # AOT: trace + XLA-compile the module now by running one forward
            # on the zero-initialized inputs (jit caches by shape, so real
            # requests hit the compiled executable); outputs are discarded
            self._executor.forward(is_train=False)

    def _apply_sharding(self):
        """Place every bound weight/aux across ``self._mesh`` per the
        partition rules (regex -> PartitionSpec,
        :func:`~mxnet_tpu.parallel.sharding.match_partition_rules`).
        Inputs are deliberately left host-staged: jit replicates
        uncommitted operands, so request arrays never perturb the
        compile cache keys."""
        from .parallel.sharding import (ShardingRules,
                                        make_shard_and_gather_fns,
                                        match_partition_rules)

        rules = self._rules
        if not isinstance(rules, ShardingRules):
            rules = ShardingRules(list(rules or []))
        self._rules = rules
        named = {n: a for n, a in self._executor.arg_dict.items()
                 if n not in self._input_names}
        for n, a in self._executor.aux_dict.items():
            named.setdefault(n, a)
        specs = match_partition_rules(
            rules, {n: a.data for n, a in named.items()})
        shard_fns, self._gather_fns = make_shard_and_gather_fns(
            specs, self._mesh)
        for n, a in named.items():
            a._set_data(shard_fns[n](a.data))

    def gather_params(self):
        """Fully-assembled host copies of the bound weights/aux (prefixed
        like :meth:`_shared_params`): the gather half of the shard/gather
        pair — checkpointing and parity checks against an unsharded
        oracle.  On an unsharded predictor this is a plain host fetch."""
        gather = getattr(self, "_gather_fns", None)
        out = {}
        for key, arr in self._shared_params().items():
            name = key.partition(":")[2]
            fn = gather.get(name) if gather else None
            out[key] = fn(arr.data) if fn is not None \
                else np.asarray(arr.asnumpy())
        return out

    # -- c_predict_api surface ------------------------------------------
    def set_input(self, key, data):
        if key not in self._input_names:
            raise ValueError("unknown input %r (inputs: %s)"
                             % (key, self._input_names))
        self._executor._stage({key: data})

    def forward(self, **inputs):
        for k, v in inputs.items():
            self.set_input(k, v)
        self.outputs = self._executor.forward(is_train=False)
        return self.outputs

    def get_output(self, index=0):
        if self.outputs is None:
            raise RuntimeError("call forward() before get_output()")
        return self.outputs[index]

    def get_output_shape(self, index=0):
        if self.outputs is not None:
            return tuple(self.outputs[index].shape)
        feed = {n: self._executor.arg_dict[n].shape
                for n in self._input_names}
        _, out_shapes, _ = self._symbol.infer_shape(**feed)
        return tuple(out_shapes[index])

    def reshape(self, input_shapes):
        """New predictor bound to different input shapes (reference
        MXPredReshape); weights are shared, the graph recompiles."""
        return Predictor(self._symbol, self._shared_params(), ctx=self._ctx,
                         input_shapes=input_shapes,
                         input_dtypes=self._input_dtypes,
                         mesh=self._mesh, rules=self._rules)

    # -- serving hooks (mxnet_tpu.serving) ------------------------------
    def _shared_params(self):
        """Bound weights/aux as a prefixed dict, sharing the underlying
        NDArrays (no copy) — the currency of reshape()/clone()."""
        params = {}
        for name, arr in self._executor.arg_dict.items():
            if name not in self._input_names:
                params["arg:" + name] = arr
        for name, arr in self._executor.aux_dict.items():
            params["aux:" + name] = arr
        return params

    def clone(self, ctx=None):
        """A new replica over the SAME weights (shared NDArrays, no HBM
        copy on the same device): its executor stages inputs
        independently, so two clones can serve concurrently."""
        shapes = {n: tuple(self._executor.arg_dict[n].shape)
                  for n in self._input_names}
        return Predictor(self._symbol, self._shared_params(),
                         ctx=ctx or self._ctx, input_shapes=shapes,
                         input_dtypes=self._input_dtypes,
                         mesh=self._mesh, rules=self._rules)

    def warm(self, batch_sizes):
        """Pre-compile one executable per leading-dim bucket by running a
        zeros forward at each size (the executor's compile cache is keyed
        by input shape) so no request triggers an XLA compile at serving
        time.  Returns the batch sizes warmed."""
        base = {n: tuple(self._executor.arg_dict[n].shape)
                for n in self._input_names}
        dtypes = {n: self._executor.arg_dict[n].dtype
                  for n in self._input_names}
        warmed = []
        for b in sorted(set(int(b) for b in batch_sizes)):
            feed = {n: nd.zeros((b,) + base[n][1:], dtype=dtypes[n],
                                ctx=self._ctx)
                    for n in base}
            self.forward(**feed)
            warmed.append(b)
        return warmed

    def health_check(self):
        """Liveness/sanity probe: one forward on zeros at the bound
        shapes; healthy iff it completes and every output is finite.
        The serving layer's circuit breaker runs this as its half-open
        probe (``Replica.probe``) before readmitting a replica to live
        traffic."""
        try:
            feed = {n: nd.zeros(tuple(self._executor.arg_dict[n].shape),
                                dtype=self._executor.arg_dict[n].dtype,
                                ctx=self._ctx)
                    for n in self._input_names}
            outs = self.forward(**feed)
            return all(bool(np.isfinite(o.asnumpy()).all()) for o in outs)
        except Exception:
            return False


# ---------------------------------------------------------------------------
# C-shaped ABI (handle-based; reference include/mxnet/c_predict_api.h)
# ---------------------------------------------------------------------------
_handles: dict = {}
_next_handle = [1]


def MXPredCreate(symbol_json_str, param_bytes, dev_type=1, dev_id=0,
                 num_input_nodes=None, input_keys=(), input_shapes=()):
    """reference c_predict_api.h:78.  dev_type 1=cpu, 2=gpu(tpu here)."""
    ctx = Context("cpu" if dev_type == 1 else "tpu", dev_id)
    shapes = dict(zip(input_keys, [tuple(s) for s in input_shapes]))
    pred = Predictor(symbol_json_str, param_bytes, ctx=ctx,
                     input_shapes=shapes)
    h = _next_handle[0]
    _next_handle[0] += 1
    _handles[h] = pred
    return h


def MXPredCreatePartialOut(symbol_json_str, param_bytes, dev_type, dev_id,
                           input_keys, input_shapes, output_keys):
    """reference c_predict_api.h:111 — restrict outputs."""
    ctx = Context("cpu" if dev_type == 1 else "tpu", dev_id)
    shapes = dict(zip(input_keys, [tuple(s) for s in input_shapes]))
    pred = Predictor(symbol_json_str, param_bytes, ctx=ctx,
                     input_shapes=shapes, output_names=list(output_keys))
    h = _next_handle[0]
    _next_handle[0] += 1
    _handles[h] = pred
    return h


def MXPredReshape(handle, input_keys, input_shapes):
    """reference c_predict_api.h:170."""
    shapes = dict(zip(input_keys, [tuple(s) for s in input_shapes]))
    pred = _handles[handle].reshape(shapes)
    h = _next_handle[0]
    _next_handle[0] += 1
    _handles[h] = pred
    return h


def MXPredGetOutputShape(handle, index=0):
    return _handles[handle].get_output_shape(index)


def MXPredSetInput(handle, key, data):
    _handles[handle].set_input(key, data)


def MXPredForward(handle):
    _handles[handle].forward()


def MXPredGetOutput(handle, index=0):
    return _handles[handle].get_output(index).asnumpy()


def MXPredFree(handle):
    _handles.pop(handle, None)
