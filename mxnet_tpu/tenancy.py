"""Per-tenant admission: quotas, fair shares, brownout exemptions.

The multi-tenant half of the serving plane (docs/SHARDED_SERVING.md
"Multi-tenant serving").  A request's tenant rides the
``X-MXTPU-Tenant`` header — stamped by loadgen, validated at the
gateway and worker front doors, and enforced *inside* the QoS class at
the same admission gates that apply brownout and the queue cap:

* **token-bucket quota** — each tenant spends one token per admission
  from a bucket refilled at its configured ``rate`` requests/second up
  to ``burst``; an empty bucket sheds with typed
  :class:`~mxnet_tpu.serving.QuotaExceeded` (never a 500, never
  another tenant's ``Overloaded``).
* **weighted-fair queue share** — when the admission queue is
  contended (>= ``MXTPU_TENANT_FAIR_FRAC`` of capacity), a tenant may
  only hold its weight's share of the queue; the overflow sheds
  ``QuotaExceeded`` while lighter tenants keep admitting.  A flooding
  tenant therefore degrades only itself — graceful degradation, not
  collapse.
* **brownout exemption** — tenants marked ``exempt`` (paying tiers)
  bypass the brownout ladder's qos_only shed and token cap; quota and
  fair-share still apply, so an exempt tenant cannot flood either.

Quotas come from one spec string (``MXTPU_TENANT_QUOTAS``)::

    MXTPU_TENANT_QUOTAS="gold:rate=50,burst=100,weight=4,exempt;free:rate=5,burst=10"

Unlisted tenants get the ``MXTPU_TENANT_DEFAULT_*`` knobs (rate 0 =
unlimited, so a deployment with no quota config behaves exactly as the
single-tenant fleet did).  All parsing is hostile-input hardened: the
tenant header is length-capped and charset-checked, and a malformed
value is a typed rejection at the HTTP edge, never an exception page.

Thread-safety: one lock guards the bucket table; it is never held
across anything blocking (the CC001 discipline).
"""
from __future__ import annotations

import math
import os
import string
import threading

__all__ = ["parse_tenant", "parse_route", "TenantSpec", "TenantGovernor",
           "governor", "reset_governor"]

# env-tunable defaults (docs/ENV_VARS.md)
_DEF_QUOTAS = os.environ.get("MXTPU_TENANT_QUOTAS", "")
_DEF_RATE = float(os.environ.get("MXTPU_TENANT_DEFAULT_RATE", "0"))
_DEF_BURST = float(os.environ.get("MXTPU_TENANT_DEFAULT_BURST", "0"))
_DEF_WEIGHT = float(os.environ.get("MXTPU_TENANT_DEFAULT_WEIGHT", "1"))
# queue fill fraction above which the weighted-fair share is enforced
_DEF_FAIR_FRAC = float(os.environ.get("MXTPU_TENANT_FAIR_FRAC", "0.5"))

# wire-name hardening: both tenant ids and route names are bounded,
# printable, and counter-safe (they feed `gen.admitted_by_tenant.<t>`
# style telemetry keys — a hostile header must not mint arbitrary keys)
_NAME_MAX = 64
_TENANT_CHARS = frozenset(string.ascii_letters + string.digits + "._-")
_ROUTE_CHARS = frozenset(string.ascii_letters + string.digits + "._-@")


def _checked_name(value, allowed, what):
    s = str(value).strip()
    if not s:
        raise ValueError("empty %s" % what)
    if len(s) > _NAME_MAX:
        raise ValueError("%s longer than %d chars" % (what, _NAME_MAX))
    if not set(s) <= allowed:
        bad = sorted(set(s) - allowed)[:4]
        raise ValueError("%s contains invalid character(s) %r" % (what, bad))
    return s


def parse_tenant(value):
    """Validate a tenant id from the ``X-MXTPU-Tenant`` header (or the
    body's ``tenant`` field).  ``None``/empty means the anonymous
    tenant.  Raises ``ValueError`` on a hostile value — oversized,
    non-printable, or outside ``[A-Za-z0-9._-]`` (non-UTF-8 header
    bytes arrive latin-1-decoded and fail the charset check) — which
    the HTTP front doors translate into a typed 400 ``BadTenant``,
    never a 500."""
    if value is None:
        return "anon"
    s = str(value).strip()
    if not s:
        return "anon"
    return _checked_name(s, _TENANT_CHARS, "tenant id")


def parse_route(value):
    """Validate a route name (``model@version`` style) from a
    ``/v1/<route>/...`` path.  Same hardening as :func:`parse_tenant`
    plus ``@``; raises ``ValueError`` on anything else."""
    if value is None:
        return "default"
    return _checked_name(value, _ROUTE_CHARS, "route name")


class TenantSpec:
    """One tenant's quota configuration."""

    __slots__ = ("name", "rate", "burst", "weight", "exempt")

    def __init__(self, name, rate=0.0, burst=0.0, weight=1.0,
                 exempt=False):
        self.name = str(name)
        self.rate = max(0.0, float(rate))
        # burst 0 with a finite rate defaults to 2 seconds of rate
        self.burst = float(burst) if float(burst) > 0 \
            else (2.0 * self.rate if self.rate > 0 else 0.0)
        self.weight = max(1e-9, float(weight))
        self.exempt = bool(exempt)

    def as_dict(self):
        return {"name": self.name, "rate": self.rate, "burst": self.burst,
                "weight": self.weight, "exempt": self.exempt}


def _parse_quota_spec(spec):
    """``"gold:rate=50,burst=100,weight=4,exempt;free:rate=5"`` ->
    ``{name: TenantSpec}``.  Raises ``ValueError`` on malformed items
    (config errors should fail loudly at startup, not at admission)."""
    out = {}
    for item in str(spec or "").split(";"):
        item = item.strip()
        if not item:
            continue
        name, _, opts = item.partition(":")
        name = parse_tenant(name)
        kw = {}
        for opt in opts.split(","):
            opt = opt.strip()
            if not opt:
                continue
            if opt == "exempt":
                kw["exempt"] = True
                continue
            k, eq, v = opt.partition("=")
            if not eq or k.strip() not in ("rate", "burst", "weight"):
                raise ValueError("MXTPU_TENANT_QUOTAS: bad option %r "
                                 "for tenant %r" % (opt, name))
            kw[k.strip()] = float(v)
        out[name] = TenantSpec(name, **kw)
    return out


class TenantGovernor:
    """Token buckets + weighted-fair shares over the configured specs.

    :meth:`check` is called at an admission gate with the current clock
    reading and (optionally) the admission queue's composition; it
    either returns the tenant's :class:`TenantSpec` or raises the typed
    :class:`~mxnet_tpu.serving.QuotaExceeded`.  Buckets are keyed by
    tenant and refill lazily on access, so idle tenants cost nothing.
    """

    def __init__(self, quotas=None, default_rate=None, default_burst=None,
                 default_weight=None, fair_frac=None):
        if isinstance(quotas, str) or quotas is None:
            quotas = _parse_quota_spec(_DEF_QUOTAS if quotas is None
                                       else quotas)
        self.specs = dict(quotas)
        self.default_rate = _DEF_RATE if default_rate is None \
            else float(default_rate)
        self.default_burst = _DEF_BURST if default_burst is None \
            else float(default_burst)
        self.default_weight = _DEF_WEIGHT if default_weight is None \
            else float(default_weight)
        self.fair_frac = _DEF_FAIR_FRAC if fair_frac is None \
            else float(fair_frac)
        self._lock = threading.Lock()
        self._buckets = {}        # tenant -> [tokens, last_refill_ts]
        self.admitted = 0
        self.shed_quota = 0
        self.shed_share = 0

    def spec_for(self, tenant):
        spec = self.specs.get(tenant)
        if spec is None:
            spec = TenantSpec(tenant, rate=self.default_rate,
                              burst=self.default_burst,
                              weight=self.default_weight)
        return spec

    def exempt(self, tenant):
        """True when ``tenant`` bypasses brownout degradation (a paying
        tier) — quota and fair-share still apply."""
        spec = self.specs.get(tenant)
        return bool(spec is not None and spec.exempt)

    def fair_cap(self, spec, queue_cap, queue_tenants):
        """Max queue slots ``spec``'s tenant may hold under contention:
        its weight's share of capacity across the tenants currently in
        the queue (plus itself)."""
        total = spec.weight
        for other in queue_tenants:
            if other != spec.name:
                total += self.spec_for(other).weight
        share = spec.weight / total
        return max(1, int(math.ceil(queue_cap * share)))

    def check(self, tenant, now, queue_len=0, queue_cap=0,
              tenant_pending=0, queue_tenants=()):
        """Spend one admission for ``tenant`` at clock reading ``now``.

        Raises :class:`~mxnet_tpu.serving.QuotaExceeded` when the
        tenant's token bucket is empty, or — with the queue contended
        (``queue_len >= fair_frac * queue_cap``) — when the tenant
        already holds its weighted-fair share of the queue
        (``tenant_pending`` of ``queue_cap`` slots, weights computed
        over ``queue_tenants``).  Returns the tenant's spec."""
        from .serving import QuotaExceeded

        spec = self.spec_for(tenant)
        # weighted-fair share first: it does not spend a token, so a
        # tenant parked at its share cap keeps its bucket for later
        if queue_cap > 0 and queue_len >= self.fair_frac * queue_cap:
            cap_n = self.fair_cap(spec, queue_cap, queue_tenants)
            # the cap binds only when it restricts below full capacity:
            # a sole tenant's share IS the whole queue, and shedding it
            # QuotaExceeded would mask the ordinary Overloaded signal
            if tenant_pending >= cap_n and cap_n < queue_cap:
                with self._lock:
                    self.shed_share += 1
                raise QuotaExceeded(
                    "tenant %r holds %d of its %d fair-share queue "
                    "slot(s) (weight %.3g, queue %d/%d)"
                    % (tenant, tenant_pending, cap_n, spec.weight,
                       queue_len, queue_cap))
        if spec.rate > 0:
            with self._lock:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = [spec.burst, now]
                tokens, last = bucket
                tokens = min(spec.burst,
                             tokens + spec.rate * max(0.0, now - last))
                if tokens < 1.0:
                    bucket[0], bucket[1] = tokens, now
                    self.shed_quota += 1
                    raise QuotaExceeded(
                        "tenant %r over quota (%.3g of burst %.3g "
                        "token(s) left at %.3g req/s)"
                        % (tenant, tokens, spec.burst, spec.rate))
                bucket[0], bucket[1] = tokens - 1.0, now
        with self._lock:
            self.admitted += 1
        return spec

    def snapshot(self):
        with self._lock:
            buckets = {t: round(b[0], 3) for t, b in self._buckets.items()}
            return {"tenants": sorted(self.specs),
                    "admitted": self.admitted,
                    "shed_quota": self.shed_quota,
                    "shed_share": self.shed_share,
                    "buckets": buckets}


_GOVERNOR = None
_GOVERNOR_LOCK = threading.Lock()


def governor():
    """The process-global :class:`TenantGovernor` (env-configured) —
    shared by every admission gate in the process, exactly like
    :func:`mxnet_tpu.serving.brownout`.  Tests :func:`reset_governor`
    it."""
    global _GOVERNOR
    with _GOVERNOR_LOCK:
        if _GOVERNOR is None:
            _GOVERNOR = TenantGovernor()
        return _GOVERNOR


def reset_governor(gov=None):
    """Replace (or re-derive from the env) the process-global governor;
    returns the new one."""
    global _GOVERNOR
    with _GOVERNOR_LOCK:
        _GOVERNOR = gov if gov is not None else TenantGovernor()
        return _GOVERNOR
