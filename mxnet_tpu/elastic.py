"""Failure detection + elastic recovery (checkpoint-based auto-resume).

The reference is thin here (SURVEY §5): in-process it surfaces async
errors at sync points (``threaded_engine.cc:474-487``), cross-process it
leans on ps-lite heartbeats and job-level restart by ``dmlc_tracker``;
there is no in-framework auto-resume.  This module fills the gap the
TPU-native way — on a TPU slice a failed host kills the whole SPMD job
and the recovery unit is *job restart from the newest checkpoint*:

* :class:`CheckpointManager` — atomic (write-temp + fsync + rename),
  versioned, checksummed, pruned checkpoints of params + optimizer/step
  state; ``save_async()`` snapshots to host memory synchronously and
  writes on a background thread (bounded to one in-flight write) so the
  training loop never blocks on the filesystem; ``latest()`` verifies
  per-file CRC32 checksums and *falls back* to the newest uncorrupted
  checkpoint, so a torn or bit-rotted write never strands the job.
* :class:`PreemptionHandler` — converts SIGTERM/SIGINT (the preemptible
  TPU-slice eviction notice) into a "checkpoint at the next step
  boundary, then exit with :data:`PREEMPTED_EXIT_CODE`" drain flow.
* :func:`supervise` — the job-level restarter (the ``dmlc_tracker``
  "restart dead jobs" analogue): reruns a training command until clean
  exit with exponential backoff + jitter between restarts, bounding
  restarts; a graceful preemption drain restarts WITHOUT charging the
  failure budget, and configurable exit codes (a deterministic assert)
  abort immediately instead of burning the budget.  Sets
  ``MXTPU_RESTART_COUNT`` so the script can tell a cold start from a
  resume.
* :class:`Watchdog` — liveness detection for hangs (a wedged collective
  never raises): if the training loop stops kicking it, the process is
  killed with a distinctive exit code so ``supervise`` restarts it.
  ``FusedTrainStep.__call__`` kicks the active watchdog automatically.
* :class:`FaultInjector` — deterministic fault injection for testing
  the recovery path (crash at step K on the first incarnation only).

Exact-resume contract: with deterministic data order and seeds, a run
that crashes and resumes must produce *bit-identical* final parameters
to an uninterrupted run — including mid-epoch crashes, provided the data
iterator's ``state_dict()`` (io.NDArrayIter / gluon DataLoader) rides
the checkpoint ``extra`` (tests/test_elastic.py asserts equality — the
same standard the dist_sync kvstore tests use).

See docs/FAULT_TOLERANCE.md for the commit protocol and env vars.
"""
from __future__ import annotations

import json
import os
import queue
import random as _pyrandom
import signal
import subprocess
import sys
import threading
import time
import zlib

import numpy as np

from .ndarray import utils as _nd_utils

__all__ = ["CheckpointManager", "FaultInjector", "InjectedFault",
           "PreemptionHandler", "PreemptionRequested", "Watchdog",
           "supervise", "active_watchdog", "install_preemption_drain",
           "WATCHDOG_EXIT_CODE", "PREEMPTED_EXIT_CODE",
           "NUMERIC_EXIT_CODE"]

WATCHDOG_EXIT_CODE = 75   # distinctive "stalled, please restart" status
PREEMPTED_EXIT_CODE = 76  # graceful drain: checkpointed, restart for free
NUMERIC_EXIT_CODE = 77    # sentinel escalation exhausted: params poisoned
#                           beyond local repair — restart from the newest
#                           verified checkpoint (retryable: supervise
#                           charges the normal failure budget)


def _log(msg):
    print("[elastic] %s" % msg, file=sys.stderr, flush=True)


def _crc32_file(path):
    """CRC32 of a file's bytes (streamed; the value recorded in the
    checkpoint meta)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _fsync_path(path):
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _fsync_dir(path):
    """Durably record the renames themselves (POSIX: rename durability
    needs a directory fsync).  Best effort — not every FS allows it."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class _SaveJob:
    def __init__(self, step, arrays, extra):
        self.step = step
        self.arrays = arrays
        self.extra = extra
        self.done = threading.Event()
        self.error = None

    def wait(self):
        self.done.wait()
        if self.error is not None:
            raise self.error


class CheckpointManager:
    """Versioned atomic checkpoints: ``prefix-####.params`` (the
    reference .params container format) + ``prefix-####.meta.json``
    (step counter, CRC32 checksums, user state such as optimizer
    hyper-state / epoch / iterator ``state_dict``).

    Atomicity: both files are written to ``.tmp`` paths, fsynced, and
    renamed; the meta file is renamed LAST and is the commit point, so a
    crash mid-save leaves the previous checkpoint as ``latest()``.

    Integrity: the meta records the params file's CRC32
    (``checksums["params"]``); ``latest()`` re-computes it and silently
    skips any step whose params are truncated/bit-flipped or whose meta
    is unreadable, returning the newest checkpoint that verifies.

    ``save_async()`` decouples the loop from the disk: the device→host
    snapshot happens synchronously (cheap d2h copy, consistent at the
    step boundary); the write+fsync+rename runs on a daemon thread with
    a bounded queue of ONE — a new ``save_async`` first waits for the
    in-flight write, so at most one checkpoint of host memory is pinned
    and writes can never pile up behind a slow disk.  Call :meth:`flush`
    before relying on the newest step being committed (it also re-raises
    any background write error).
    """

    def __init__(self, prefix, keep_n=3):
        self.prefix = prefix
        self.keep_n = keep_n
        d = os.path.dirname(os.path.abspath(prefix))
        os.makedirs(d, exist_ok=True)
        self._dir = d
        self._queue = None
        self._thread = None
        self._inflight = None

    def _params_path(self, step):
        return "%s-%04d.params" % (self.prefix, step)

    def _meta_path(self, step):
        return "%s-%04d.meta.json" % (self.prefix, step)

    # -- write path -------------------------------------------------------
    @staticmethod
    def _snapshot(params):
        """Device→host copy of a name->NDArray (or numpy) dict — the only
        part of an async save that must happen at the step boundary."""
        out = {}
        for k, v in dict(params).items():
            out[k] = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        return out

    def _write(self, step, arrays, extra):
        """Write+fsync+rename one checkpoint (runs on the caller's thread
        for ``save`` and on the writer thread for ``save_async``)."""
        pp, mp = self._params_path(step), self._meta_path(step)
        _nd_utils.save(pp + ".tmp", arrays)
        _fsync_path(pp + ".tmp")
        crc = _crc32_file(pp + ".tmp")
        os.replace(pp + ".tmp", pp)
        # fault injection: die between the params rename and the meta
        # rename — the torn-save window the meta-last protocol exists for
        fi_step = int(os.environ.get("MXTPU_FI_CRASH_AFTER_PARAMS", "-1"))
        if (step == fi_step
                and int(os.environ.get("MXTPU_RESTART_COUNT", "0")) == 0):
            os._exit(23)
        with open(mp + ".tmp", "w") as f:
            json.dump({"step": int(step), "extra": extra or {},
                       "checksums": {"params": crc}}, f)
        _fsync_path(mp + ".tmp")
        os.replace(mp + ".tmp", mp)  # commit point
        _fsync_dir(self._dir)
        self._prune()

    def save(self, step, params, extra=None):
        """Synchronous checkpoint.  params: dict name -> NDArray (or
        numpy); extra: JSON-able dict.  Orders after any in-flight async
        write (so sync and async saves never interleave)."""
        self.flush()
        self._write(step, self._snapshot(params), extra)

    def save_async(self, step, params, extra=None):
        """Checkpoint without blocking the training loop on the disk.

        Synchronously snapshots ``params`` to host memory, waits for the
        previous async write (bounded queue of 1), then hands the write
        to the background thread.  Returns a handle with ``wait()``.
        Background errors surface on the next ``save_async``/``flush``.
        """
        arrays = self._snapshot(params)
        self.flush()  # bound: at most one write in flight
        if self._thread is None:
            self._queue = queue.Queue(maxsize=1)
            self._thread = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True)
            self._thread.start()
        job = _SaveJob(step, arrays, extra)
        self._inflight = job
        self._queue.put(job)
        return job

    def flush(self):
        """Wait for the in-flight async write; re-raise its error."""
        job, self._inflight = self._inflight, None
        if job is not None:
            job.wait()

    def _writer_loop(self):
        while True:
            job = self._queue.get()
            try:
                self._write(job.step, job.arrays, job.extra)
            except BaseException as e:  # surfaced by flush()
                job.error = e
            finally:
                job.done.set()

    # -- read path --------------------------------------------------------
    def steps(self):
        """Committed checkpoint steps, ascending (a meta file plus an
        existing params file; integrity is verified by ``latest()``)."""
        d = os.path.dirname(os.path.abspath(self.prefix)) or "."
        base = os.path.basename(self.prefix)
        out = []
        for fn in os.listdir(d):
            if fn.startswith(base + "-") and fn.endswith(".meta.json"):
                num = fn[len(base) + 1:-len(".meta.json")]
                if num.isdigit() and os.path.exists(
                        self._params_path(int(num))):
                    out.append(int(num))
        return sorted(out)

    def _verify_meta(self, step):
        """Parsed meta if the checkpoint passes integrity checks, else
        None (with a warning naming the failure)."""
        try:
            with open(self._meta_path(step)) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            _log("checkpoint step %d: unreadable meta (%s) — skipping"
                 % (step, e))
            return None
        want = (meta.get("checksums") or {}).get("params")
        if want is not None:
            try:
                got = _crc32_file(self._params_path(step))
            except OSError as e:
                _log("checkpoint step %d: unreadable params (%s) — "
                     "skipping" % (step, e))
                return None
            if got != want:
                _log("checkpoint step %d: params checksum mismatch "
                     "(recorded %08x, file %08x) — skipping"
                     % (step, want, got))
                return None
        return meta

    def latest(self):
        """(step, params, extra) of the newest *verified* checkpoint, or
        None on a cold start.

        Walks committed steps newest-first; a step with a truncated or
        bit-flipped params file (checksum mismatch), an invalid meta
        JSON, or an unloadable params container is skipped with a
        warning and the previous committed checkpoint wins."""
        for step in reversed(self.steps()):
            meta = self._verify_meta(step)
            if meta is None:
                continue
            try:
                params = _nd_utils.load(self._params_path(step))
            except Exception as e:  # pre-checksum checkpoints
                _log("checkpoint step %d: params failed to load (%s) — "
                     "skipping" % (step, e))
                continue
            return step, params, meta.get("extra", {})
        return None

    def _prune(self):
        for s in self.steps()[:-self.keep_n]:
            for p in (self._params_path(s), self._meta_path(s)):
                try:
                    os.remove(p)
                except OSError:
                    pass


class InjectedFault(RuntimeError):
    pass


class FaultInjector:
    """Deterministic crash for recovery tests: dies at step
    ``MXTPU_FI_AT_STEP`` but only on incarnation ``MXTPU_FI_AT_RESTART``
    (default 0 — the first run), so the supervised rerun survives.
    ``MXTPU_FI_AT_RESTART=-1`` faults on every incarnation (for testing
    restart-budget exhaustion)."""

    def __init__(self):
        self.at_step = int(os.environ.get("MXTPU_FI_AT_STEP", "-1"))
        self.at_restart = int(os.environ.get("MXTPU_FI_AT_RESTART", "0"))
        self.incarnation = int(os.environ.get("MXTPU_RESTART_COUNT", "0"))

    def maybe_fail(self, step):
        if step == self.at_step and self.at_restart in (-1,
                                                        self.incarnation):
            raise InjectedFault("injected fault at step %d (incarnation "
                                "%d)" % (step, self.incarnation))


class PreemptionRequested(RuntimeError):
    """Raised at a step boundary (FusedTrainStep / Trainer) after a
    drain signal arrived — unwind to the drain handler, checkpoint, and
    exit with :data:`PREEMPTED_EXIT_CODE`."""


class PreemptionHandler:
    """Graceful SIGTERM/SIGINT drain for preemptible slices.

    The first signal only sets a flag; the training loop observes it at
    the next step boundary (``requested`` / ``check()`` — FusedTrainStep
    and Trainer check automatically when handed a handler) and calls
    :meth:`drain` to write a final checkpoint and exit with
    :data:`PREEMPTED_EXIT_CODE`, which :func:`supervise` restarts
    without charging the failure budget.  A second signal while draining
    exits immediately (the eviction deadline is near; better to lose the
    tail than be SIGKILLed mid-write).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 exit_code=PREEMPTED_EXIT_CODE):
        self._signals = tuple(signals)
        self.exit_code = exit_code
        self._requested = threading.Event()
        self._signal_count = 0
        self._prev = {}
        self._installed = False
        # registration happens during setup, before install() arms the
        # signal; Python delivers signals on the main thread, so the
        # iteration in _on_signal never overlaps add_callback
        self._callbacks = []  # mxlint: not-shared — registered pre-install, read on main thread

    def install(self):
        """Register the signal handlers (main thread only — CPython
        restriction).  Returns self for chaining."""
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def add_callback(self, fn):
        """Register ``fn`` to run from the signal handler on the FIRST
        drain signal (e.g. ``ModelServer._drain_flag.set`` so admission
        closes immediately, before the step boundary).  ``fn`` runs in
        signal-handler context: it must be async-signal safe — an atomic
        flag/Event set, never lock acquisition or I/O."""
        self._callbacks.append(fn)
        return self

    def _on_signal(self, signum, frame):
        self._signal_count += 1
        if self._signal_count > 1:
            os._exit(self.exit_code)  # impatient second signal
        self._requested.set()
        for fn in self._callbacks:
            try:
                fn()
            except Exception:
                pass  # a broken callback must not mask the drain
        _log("received signal %d: draining at the next step boundary"
             % signum)

    @property
    def requested(self):
        return self._requested.is_set()

    def check(self):
        """Raise :class:`PreemptionRequested` if a drain was requested
        (call at step boundaries)."""
        if self._requested.is_set():
            raise PreemptionRequested(
                "preemption drain requested (signal received)")

    def drain(self, checkpoint_fn=None):
        """Write the final checkpoint (``checkpoint_fn``) and exit with
        the distinctive drain status."""
        if checkpoint_fn is not None:
            checkpoint_fn()
        _log("drain checkpoint written; exiting rc=%d" % self.exit_code)
        sys.exit(self.exit_code)


def install_preemption_drain(drain_flag_set, handler=None):
    """Wire a server's drain flag into SIGTERM/SIGINT (the rc-76
    graceful-drain contract, docs/FAULT_TOLERANCE.md).

    The one shared implementation behind
    ``ModelServer.install_preemption_drain`` /
    ``GenerationServer.install_preemption_drain`` and the fleet worker
    entrypoint: installs a fresh :class:`PreemptionHandler` when none is
    given (main thread only — CPython signal restriction) and registers
    ``drain_flag_set`` to run on the FIRST drain signal so admission
    closes immediately, before the step boundary.  ``drain_flag_set``
    runs in signal-handler context: it must be async-signal safe (an
    Event/flag set, never lock acquisition or I/O).  Returns the
    handler.
    """
    if handler is None:
        handler = PreemptionHandler().install()
    handler.add_callback(drain_flag_set)
    return handler


_active_watchdog = None


def active_watchdog():
    """The most recently started (and not stopped) Watchdog, or None.
    ``FusedTrainStep.__call__`` kicks it automatically."""
    return _active_watchdog


class Watchdog:
    """Hang detector: a daemon thread that calls ``on_stall`` (default:
    ``os._exit(WATCHDOG_EXIT_CODE)``) if ``kick()`` is not called within
    ``timeout`` seconds.  A wedged XLA collective or a dead tunnel hangs
    forever without raising — exiting with a distinctive status converts
    the hang into a restartable failure for :func:`supervise`.

    ``start()`` on an already-started watchdog raises (a silent double
    start would leave an orphan watcher holding a stale deadline);
    ``stop()`` joins the watcher thread so no timer survives it."""

    def __init__(self, timeout, on_stall=None):
        self.timeout = timeout
        self.on_stall = on_stall or (
            lambda: os._exit(WATCHDOG_EXIT_CODE))
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._started = False
        self._thread = threading.Thread(target=self._watch, daemon=True)

    def start(self):
        global _active_watchdog
        if self._started:
            raise RuntimeError("Watchdog.start() called twice — one "
                               "watchdog, one watcher thread")
        self._started = True
        self._last = time.monotonic()
        self._thread.start()
        _active_watchdog = self
        return self

    def kick(self):
        self._last = time.monotonic()

    def stop(self):
        global _active_watchdog
        self._stop.set()
        if self._started and self._thread is not threading.current_thread():
            self._thread.join()
        if _active_watchdog is self:
            _active_watchdog = None

    def _watch(self):
        while not self._stop.wait(min(self.timeout / 4.0, 1.0)):
            if time.monotonic() - self._last > self.timeout:
                self.on_stall()
                return


def _backoff_delay(failures, base, cap=30.0):
    """Exponential backoff with jitter for restart ``failures`` (1-based):
    ``min(cap, base * 2**(failures-1))`` scaled by uniform [0.5, 1.0) —
    decorrelates a gang of workers restarting off the same fault."""
    if base <= 0:
        return 0.0
    return min(float(cap), float(base) * (2.0 ** (failures - 1))) \
        * (0.5 + 0.5 * _pyrandom.random())


def supervise(argv, max_restarts=3, env=None, logger=None, backoff=None,
              backoff_cap=30.0, nonretryable=None, max_preemptions=1000):
    """Run ``argv`` until clean exit, restarting on failure (job-level
    elasticity — the dmlc_tracker restart analogue, reference
    ``tools/launch.py`` job lifecycle).

    Each incarnation gets ``MXTPU_RESTART_COUNT`` in its env; the
    training script resumes from ``CheckpointManager.latest()``.

    * Failures restart after exponential backoff with jitter
      (``backoff`` base seconds, default ``MXTPU_RESTART_BACKOFF`` or
      1.0; capped at ``backoff_cap``).
    * rc == :data:`PREEMPTED_EXIT_CODE` (graceful drain) restarts
      immediately and does NOT count against ``max_restarts`` — a
      preempted worker did nothing wrong (bounded by
      ``max_preemptions`` as a runaway stop).
    * An rc in ``nonretryable`` (default: the comma list in
      ``MXTPU_NONRETRYABLE_EXIT_CODES``) raises immediately — a
      deterministic assertion failure must not burn the whole budget.

    Returns the number of restarts used.  Raises ``RuntimeError`` when
    the budget is exhausted or a non-retryable code is seen.
    """
    log = logger or (lambda msg: print("[supervise] %s" % msg,
                                       file=sys.stderr, flush=True))
    base_env = dict(env if env is not None else os.environ)
    if backoff is None:
        backoff = float(base_env.get(
            "MXTPU_RESTART_BACKOFF",
            os.environ.get("MXTPU_RESTART_BACKOFF", "1.0")))
    if nonretryable is None:
        raw = base_env.get(
            "MXTPU_NONRETRYABLE_EXIT_CODES",
            os.environ.get("MXTPU_NONRETRYABLE_EXIT_CODES", ""))
        nonretryable = {int(x) for x in raw.split(",") if x.strip()}
    nonretryable = frozenset(nonretryable)

    failures = 0
    preemptions = 0
    incarnation = 0
    while True:
        run_env = {**base_env, "MXTPU_RESTART_COUNT": str(incarnation)}
        r = subprocess.run(list(argv), env=run_env)
        rc = r.returncode
        if rc == 0:
            return incarnation
        if rc in nonretryable:
            raise RuntimeError(
                "job exited with non-retryable rc=%d (incarnation %d)"
                % (rc, incarnation))
        if rc == PREEMPTED_EXIT_CODE:
            preemptions += 1
            if preemptions > max_preemptions:
                raise RuntimeError(
                    "job preempted %d times — giving up" % preemptions)
            log("incarnation %d drained on preemption (rc=%d): "
                "restarting, failure budget untouched" % (incarnation, rc))
        else:
            failures += 1
            if failures > max_restarts:
                raise RuntimeError("job failed after %d restarts"
                                   % max_restarts)
            delay = _backoff_delay(failures, backoff, backoff_cap)
            log("incarnation %d exited rc=%d%s; restart %d/%d in %.2fs"
                % (incarnation, rc,
                   " (watchdog stall)" if rc == WATCHDOG_EXIT_CODE else "",
                   failures, max_restarts, delay))
            if delay:
                time.sleep(delay)
        incarnation += 1
