"""Failure detection + elastic recovery (checkpoint-based auto-resume).

The reference is thin here (SURVEY §5): in-process it surfaces async
errors at sync points (``threaded_engine.cc:474-487``), cross-process it
leans on ps-lite heartbeats and job-level restart by ``dmlc_tracker``;
there is no in-framework auto-resume.  This module fills the gap the
TPU-native way — on a TPU slice a failed host kills the whole SPMD job
and the recovery unit is *job restart from the newest checkpoint*:

* :class:`CheckpointManager` — atomic (write-temp + rename), versioned,
  pruned checkpoints of params + optimizer/step state; ``latest()``
  gives the resume point after an unclean death.
* :func:`supervise` — the job-level restarter (the ``dmlc_tracker``
  "restart dead jobs" analogue): reruns a training command until clean
  exit, bounding restarts; sets ``MXTPU_RESTART_COUNT`` so the script
  can tell a cold start from a resume.
* :class:`Watchdog` — liveness detection for hangs (a wedged collective
  never raises): if the training loop stops kicking it, the process is
  killed with a distinctive exit code so ``supervise`` restarts it.
* :class:`FaultInjector` — deterministic fault injection for testing
  the recovery path (crash at step K on the first incarnation only).

Exact-resume contract: with deterministic data order and seeds, a run
that crashes and resumes must produce *bit-identical* final parameters
to an uninterrupted run (tests/test_elastic.py asserts equality — the
same standard the dist_sync kvstore tests use).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

from .ndarray import utils as _nd_utils

__all__ = ["CheckpointManager", "FaultInjector", "InjectedFault",
           "Watchdog", "supervise", "WATCHDOG_EXIT_CODE"]

WATCHDOG_EXIT_CODE = 75  # distinctive "stalled, please restart" status


class CheckpointManager:
    """Versioned atomic checkpoints: ``prefix-####.params`` (the
    reference .params container format) + ``prefix-####.meta.json``
    (step counter, user state such as optimizer hyper-state / epoch).

    Atomicity: both files are written to ``.tmp`` paths and renamed;
    the meta file is renamed LAST and is the commit point, so a crash
    mid-save leaves the previous checkpoint as ``latest()``.
    """

    def __init__(self, prefix, keep_n=3):
        self.prefix = prefix
        self.keep_n = keep_n
        d = os.path.dirname(os.path.abspath(prefix))
        os.makedirs(d, exist_ok=True)

    def _params_path(self, step):
        return "%s-%04d.params" % (self.prefix, step)

    def _meta_path(self, step):
        return "%s-%04d.meta.json" % (self.prefix, step)

    def save(self, step, params, extra=None):
        """params: dict name -> NDArray; extra: JSON-able dict."""
        pp, mp = self._params_path(step), self._meta_path(step)
        _nd_utils.save(pp + ".tmp", dict(params))
        os.replace(pp + ".tmp", pp)
        with open(mp + ".tmp", "w") as f:
            json.dump({"step": int(step), "extra": extra or {}}, f)
        os.replace(mp + ".tmp", mp)  # commit point
        self._prune()

    def steps(self):
        """Committed checkpoint steps, ascending."""
        d = os.path.dirname(os.path.abspath(self.prefix)) or "."
        base = os.path.basename(self.prefix)
        out = []
        for fn in os.listdir(d):
            if fn.startswith(base + "-") and fn.endswith(".meta.json"):
                num = fn[len(base) + 1:-len(".meta.json")]
                if num.isdigit() and os.path.exists(
                        self._params_path(int(num))):
                    out.append(int(num))
        return sorted(out)

    def latest(self):
        """(step, params, extra) of the newest committed checkpoint, or
        None on a cold start."""
        steps = self.steps()
        if not steps:
            return None
        step = steps[-1]
        with open(self._meta_path(step)) as f:
            meta = json.load(f)
        params = _nd_utils.load(self._params_path(step))
        return step, params, meta.get("extra", {})

    def _prune(self):
        for s in self.steps()[:-self.keep_n]:
            for p in (self._params_path(s), self._meta_path(s)):
                try:
                    os.remove(p)
                except OSError:
                    pass


class InjectedFault(RuntimeError):
    pass


class FaultInjector:
    """Deterministic crash for recovery tests: dies at step
    ``MXTPU_FI_AT_STEP`` but only on incarnation ``MXTPU_FI_AT_RESTART``
    (default 0 — the first run), so the supervised rerun survives.
    ``MXTPU_FI_AT_RESTART=-1`` faults on every incarnation (for testing
    restart-budget exhaustion)."""

    def __init__(self):
        self.at_step = int(os.environ.get("MXTPU_FI_AT_STEP", "-1"))
        self.at_restart = int(os.environ.get("MXTPU_FI_AT_RESTART", "0"))
        self.incarnation = int(os.environ.get("MXTPU_RESTART_COUNT", "0"))

    def maybe_fail(self, step):
        if step == self.at_step and self.at_restart in (-1,
                                                        self.incarnation):
            raise InjectedFault("injected fault at step %d (incarnation "
                                "%d)" % (step, self.incarnation))


class Watchdog:
    """Hang detector: a daemon thread that calls ``on_stall`` (default:
    ``os._exit(WATCHDOG_EXIT_CODE)``) if ``kick()`` is not called within
    ``timeout`` seconds.  A wedged XLA collective or a dead tunnel hangs
    forever without raising — exiting with a distinctive status converts
    the hang into a restartable failure for :func:`supervise`."""

    def __init__(self, timeout, on_stall=None):
        self.timeout = timeout
        self.on_stall = on_stall or (
            lambda: os._exit(WATCHDOG_EXIT_CODE))
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._watch, daemon=True)

    def start(self):
        self._last = time.monotonic()
        self._thread.start()
        return self

    def kick(self):
        self._last = time.monotonic()

    def stop(self):
        self._stop.set()

    def _watch(self):
        while not self._stop.wait(min(self.timeout / 4.0, 1.0)):
            if time.monotonic() - self._last > self.timeout:
                self.on_stall()
                return


def supervise(argv, max_restarts=3, env=None, logger=None):
    """Run ``argv`` until clean exit, restarting on failure (job-level
    elasticity — the dmlc_tracker restart analogue, reference
    ``tools/launch.py`` job lifecycle).

    Each incarnation gets ``MXTPU_RESTART_COUNT`` in its env; the
    training script resumes from ``CheckpointManager.latest()``.
    Returns the number of restarts used.  Raises ``RuntimeError`` when
    the budget is exhausted.
    """
    log = logger or (lambda msg: print("[supervise] %s" % msg,
                                       file=sys.stderr, flush=True))
    base_env = dict(env if env is not None else os.environ)
    for restart in range(max_restarts + 1):
        run_env = {**base_env, "MXTPU_RESTART_COUNT": str(restart)}
        r = subprocess.run(list(argv), env=run_env)
        if r.returncode == 0:
            return restart
        log("incarnation %d exited rc=%d%s" %
            (restart, r.returncode,
             " (watchdog stall)" if r.returncode == WATCHDOG_EXIT_CODE
             else ""))
    raise RuntimeError("job failed after %d restarts" % max_restarts)
