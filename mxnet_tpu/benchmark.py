"""Throughput measurement utilities (reference:
``example/image-classification/benchmark_score.py`` +
``docs/faq/perf.md`` methodology, the scripts behind BASELINE.md).

Two disciplines, because the dispatch path and the device disagree about
what "throughput" means when the host link is slow or jittery:

* :func:`compiled_throughput` — the K-step inference loop is compiled
  into ONE XLA module (``lax.fori_loop`` around the block's traced
  forward) with a runtime-zero probe chaining step *i*'s output into
  step *i+1*'s input.  One dispatch + one scalar fetch per draw, so the
  number measures the device, not the host link.  The chain makes every
  iteration data-dependent on the previous one: XLA cannot hoist the
  network out of the loop (the carry changes each step as far as the
  compiler can prove — the zero arrives at run time) and cannot fold
  ``x * zero`` away (it is not a literal).  This is the stable gate
  metric: repeated draws agree within a few percent.
* :func:`percall_throughput` — the user path: one framework dispatch per
  ``net(x)`` call, timed wall-clock with a host value fetch as the
  barrier.  On local hardware XLA's async dispatch pipelines this to
  device speed; over a remote tunnel it measures the tunnel, with up to
  2x draw-to-draw jitter.  Published with its spread, never as a gate.

Both report the MEDIAN of ``draws`` timed repetitions with min/max
alongside, per VERDICT r3 ("median-of-k with documented k").
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd
from .ndarray.ndarray import NDArray

__all__ = ["compiled_throughput", "percall_throughput"]


def _first_out(out):
    if isinstance(out, (list, tuple)):
        out = out[0]
    return out.data


def _summarize(draws, items_per_draw):
    rates = [items_per_draw / dt for dt in draws]
    return {
        "median": float(np.median(rates)),
        "min": float(min(rates)),
        "max": float(max(rates)),
        "draws": len(rates),
    }


def _compiled_draw(net, x, steps):
    """Compile the K-step chained loop ONCE; return a zero-arg callable
    that runs one timed draw and returns ELAPSED SECONDS."""
    from .gluon.block import params_as_trace_inputs

    batch = x.shape[0]
    # parameters ride as explicit jit arguments (not trace constants):
    # a VGG-sized weight set embedded as HLO constants overflows the
    # remote-compile request body
    params = (list(net.collect_params().values())
              if hasattr(net, "collect_params") else [])
    pdatas = [p.data().data for p in params]

    def loop(xd, zero, pvals):
        stand_ins = [NDArray(v) for v in pvals]
        with params_as_trace_inputs(params, stand_ins):
            def body(_, carry):
                out = _first_out(net(NDArray(carry)))
                probe = jnp.ravel(out)[0].astype(carry.dtype)
                return carry + probe * zero
            return jax.lax.fori_loop(0, steps, body, xd)

    jloop = jax.jit(loop)
    zero = jnp.zeros((), dtype=x.data.dtype)
    with autograd.pause(train_mode=False):
        for _ in range(2):  # compile, then one warm draw off the clock
            r = jloop(x.data, zero, pdatas)
            np.asarray(jax.device_get(r.ravel()[0]))

    def draw():
        """One timed draw; returns elapsed seconds."""
        with autograd.pause(train_mode=False):
            t0 = time.perf_counter()
            r = jloop(x.data, zero, pdatas)
            np.asarray(jax.device_get(r.ravel()[0]))
            return time.perf_counter() - t0
    return draw


def compiled_throughput(net, x, steps=30, draws=5):
    """items/sec of ``net`` forward on batch ``x``, K steps per compiled
    dispatch; returns {median,min,max,draws} over ``draws`` repetitions.

    ``net`` must be callable on an NDArray inside a trace (hybridized
    Gluon blocks are); runs in inference mode (``autograd.pause``).
    """
    batch = x.shape[0]
    one_draw = _compiled_draw(net, x, steps)
    times = [one_draw() for _ in range(draws)]
    return _summarize(times, batch * steps)


def interleaved_throughput(pairs, steps=20, reps=3):
    """A/B measurement immune to chip/session drift: compile each
    (net, x) loop ONCE, then alternate timed draws A,B,A,B,...
    Returns a list of per-pair median items/sec."""
    draws = [_compiled_draw(net, x, steps) for net, x in pairs]
    results = [[] for _ in pairs]
    for _ in range(reps):
        for i, d in enumerate(draws):
            results[i].append(pairs[i][1].shape[0] * steps / d())
    return [float(np.median(r)) for r in results]


def percall_throughput(net, x, steps=30, draws=5):
    """items/sec of the per-dispatch user path: ``steps`` framework-level
    ``net(x)`` calls per draw, chained through a runtime-zero probe so
    identical launches cannot be deduped, ended by a host scalar fetch
    (the real execution barrier — a ready-barrier alone can read
    impossibly fast through a remote runtime)."""
    batch = x.shape[0]
    zero = NDArray(jnp.zeros((1,), dtype=x.data.dtype))
    with autograd.pause(train_mode=False):
        out = net(x)
        if isinstance(out, (list, tuple)):
            out = out[0]
        out.asnumpy()  # compile
        times = []
        for _ in range(draws):
            xi = x
            t0 = time.perf_counter()
            for _ in range(steps):
                out = net(xi)
                if isinstance(out, (list, tuple)):
                    out = out[0]
                xi = xi + out[0, 0] * zero
            float(out[0, 0].asnumpy())
            times.append(time.perf_counter() - t0)
    return _summarize(times, batch * steps)
