"""Shared plumbing: dtypes, shapes, errors, string/int name registry.

Reference parity: ``python/mxnet/base.py`` (ctypes plumbing) — here there is no C
ABI to marshal through (JAX *is* the runtime), so this module only keeps the
dtype/shape conventions and the error type.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "DTYPE_MAP", "np_dtype", "string_types",
           "encode_rng_state", "decode_rng_state"]

string_types = (str,)


class MXNetError(RuntimeError):
    """Framework error type (reference: dmlc error -> MXNetError)."""


# Reference dtype enum (mshadow/base.h TypeFlag order) — kept so that saved-model
# metadata and operator dtype attributes use the same integer codes.
DTYPE_MAP = {
    0: np.float32,
    1: np.float64,
    2: np.float16,
    3: np.uint8,
    4: np.int32,
    5: np.int8,
    6: np.int64,
    # TPU-native additions (no reference equivalent):
    7: np.dtype("bfloat16") if hasattr(np, "bfloat16") else "bfloat16",
    8: np.bool_,
}
_DTYPE_TO_CODE = {}
for _code, _dt in DTYPE_MAP.items():
    try:
        _DTYPE_TO_CODE[np.dtype(_dt)] = _code
    except TypeError:
        pass


def np_dtype(dtype):
    """Normalize int code / str / np.dtype to np.dtype."""
    if isinstance(dtype, int):
        return np.dtype(DTYPE_MAP[dtype])
    if dtype is None:
        return np.dtype(np.float32)
    try:
        return np.dtype(dtype)
    except TypeError:
        # bfloat16 via ml_dtypes
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, str(dtype)))


def dtype_code(dtype):
    return _DTYPE_TO_CODE[np.dtype(dtype)]


try:  # private but stable across the jax versions we support; resolved
    # at import so a relocation fails LOUDLY here instead of silently
    # disabling every tracer-poisoning guard built on in_user_trace()
    from jax._src.core import trace_state_clean as _trace_state_clean
except ImportError as _e:  # pragma: no cover - depends on jax version
    raise ImportError(
        "jax._src.core.trace_state_clean moved in this jax version; "
        "update mxnet_tpu.base.in_user_trace for the new location "
        "(the trace-escape guards in registry/random/SymbolBlock "
        "depend on it): %s" % _e)


def in_user_trace():
    """True when user-level jax is tracing (jit/scan/grad over framework
    calls).  Imperative caching/mutation must not capture tracers then."""
    return not _trace_state_clean()


def encode_rng_state(rng):
    """JSON-able snapshot of a ``np.random.RandomState`` (checkpointed by
    the data-iterator ``state_dict`` protocol so shuffle order of FUTURE
    epochs survives a mid-epoch resume)."""
    kind, keys, pos, has_gauss, cached = rng.get_state()
    return [kind, [int(k) for k in keys], int(pos), int(has_gauss),
            float(cached)]


def decode_rng_state(state):
    """Inverse of :func:`encode_rng_state` (a set_state-compatible tuple)."""
    kind, keys, pos, has_gauss, cached = state
    return (kind, np.asarray(keys, dtype=np.uint32), int(pos),
            int(has_gauss), float(cached))
