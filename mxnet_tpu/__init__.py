"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Built new for TPU (JAX/XLA/Pallas/pjit idioms) to the blueprint in SURVEY.md;
reference for API/behavior parity: RustyRaptor/incubator-mxnet (read-only
snapshot).  Import convention mirrors the reference::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""
from __future__ import annotations

__version__ = "0.1.0"

# arm the runtime lock-order sanitizer (MXTPU_LOCKDEP) before ANY other
# framework import — the factories must be wrapped before the first
# module-level lock is created, and lockdep itself is stdlib-only
from . import lockdep  # noqa: F401

lockdep.install_from_env()

# arm the runtime lockset race sanitizer (MXTPU_RACECHECK) next — its
# lock-identity tokens must wrap whatever factory is live (stacking on
# lockdep's), and before any tracked class is instantiated
from . import racecheck  # noqa: F401

racecheck.install_from_env()

# arm the runtime resource-leak sanitizer (MXTPU_LEAKCHECK) the same way
# — stdlib-only, and its track/untrack hooks must be live before the
# first allocator/breaker/future exists
from . import leakcheck  # noqa: F401

leakcheck.install_from_env()

# arm the persistent XLA compilation cache (MXNET_COMPILE_CACHE) before
# anything can trigger a compile — jax reads the cache dir at compile time,
# so this must precede the first jitted call anywhere in the process
from .runtime import init_compile_cache as _init_compile_cache

_init_compile_cache()

from ._dist import init_from_env as _dist_init_from_env

_dist_init_from_env()  # multi-worker bootstrap (mxnet_tpu.tools.launch)

from .base import MXNetError  # noqa: F401
from .attribute import AttrScope  # noqa: F401
from .context import (Context, cpu, gpu, tpu, cpu_pinned, num_gpus,  # noqa: F401
                      num_tpus, current_context)
from . import ops  # noqa: F401  (registers the op corpus)
from . import operator  # noqa: F401  (registers 'Custom' before nd codegen)
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from . import autograd  # noqa: F401
from . import random  # noqa: F401
from .ndarray import NDArray  # noqa: F401
from . import initializer  # noqa: F401
from . import initializer as init  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import optimizer  # noqa: F401
from . import optimizer as opt  # noqa: F401
from . import metric  # noqa: F401
from . import kvstore  # noqa: F401
from .kvstore import create as _kv_create  # noqa: F401
from . import kvstore as kv  # noqa: F401
from . import gluon  # noqa: F401
from . import symbol  # noqa: F401
from . import symbol as sym  # noqa: F401
from .executor import Executor  # noqa: F401
from . import io  # noqa: F401
from . import recordio  # noqa: F401
from . import model  # noqa: F401
from . import module  # noqa: F401
from . import module as mod  # noqa: F401
from . import callback  # noqa: F401
from . import predict  # noqa: F401
from . import image  # noqa: F401
from . import profiler  # noqa: F401
from . import telemetry  # noqa: F401
from . import dispatch  # noqa: F401
from . import contrib  # noqa: F401
from . import monitor  # noqa: F401
from .monitor import Monitor  # noqa: F401
from . import visualization  # noqa: F401
from . import visualization as viz  # noqa: F401
from .config import config  # noqa: F401  (mx.config = the knob registry;
#                            the module stays importable as mxnet_tpu.config
#                            via sys.modules and has the same describe())
from . import runtime  # noqa: F401
from . import rtc  # noqa: F401
from . import elastic  # noqa: F401
from . import chaos  # noqa: F401
from . import sentinel  # noqa: F401
from . import serving  # noqa: F401
from . import generation  # noqa: F401
from . import fleet  # noqa: F401
from . import gateway  # noqa: F401
from . import benchmark  # noqa: F401

# everything registered up to here is the shipped op corpus; later
# registrations are user ops (operator.register / rtc.PallasModule)
ops.registry.freeze_builtins()

if config.profiler_autostart:
    profiler.start()

# JSONL exporter / localhost metrics endpoint, when the MXNET_TELEMETRY_*
# knobs ask for them (both default off — docs/OBSERVABILITY.md)
telemetry.init_from_env()
