"""Optimizer package (reference: ``python/mxnet/optimizer/``)."""
from .optimizer import (Optimizer, Updater, get_updater, create, register,  # noqa: F401
                        SGD, Signum, SignSGD, FTML, DCASGD, NAG, SGLD, Adam,
                        AdaGrad, RMSProp, AdaDelta, Ftrl, Adamax, Nadam,
                        AdamW, LBSGD, LAMB, Test)
from .loss_scaler import DynamicLossScaler  # noqa: F401
from . import contrib  # noqa: F401

opt_registry = Optimizer.opt_registry
