"""Contrib optimizers (reference: ``python/mxnet/optimizer/contrib.py``)."""
from __future__ import annotations

from ..ndarray import zeros
from ..ops.registry import invoke
from .optimizer import Optimizer, register

__all__ = ["GroupAdaGrad"]


@register
class GroupAdaGrad(Optimizer):
    """AdaGrad with per-row (group) accumulation — used for embeddings
    (reference: contrib.py GroupAdaGrad over contrib group_adagrad_update)."""

    def __init__(self, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        shape = (weight.shape[0],) + (1,) * (len(weight.shape) - 1) \
            if len(weight.shape) > 1 else weight.shape
        return zeros(shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        assert self._get_wd(index) == 0, \
            "Weight decay is not supported for GroupAdaGrad"
        kwargs = self._common_kwargs(index)
        kwargs.pop("wd")
        invoke("group_adagrad_update", [weight, grad, state],
               dict(epsilon=self.float_stable_eps, **kwargs))
