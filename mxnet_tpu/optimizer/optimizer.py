"""Optimizers: frontend classes dispatching to fused update ops.

Reference parity: ``python/mxnet/optimizer/optimizer.py`` (17 @register
classes, SGD:498 ... Nadam:1521, Updater:1608 with fp16 master weights) over
``src/operator/optimizer_op.cc`` fused kernels.  TPU-native: every
``update()`` invokes one registered jit'd update op
(``mxnet_tpu/ops/optimizer_ops.py``); ``lr``/``wd``/step counters are traced
scalars so schedules never trigger recompilation.
"""
from __future__ import annotations

import logging
import math
import os
import pickle

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray, zeros
from ..ops.registry import invoke


_NO_ROWS = object()  # sentinel: row-sparse grad storing zero rows


def _row_sparse_indices(grad):
    """The gradient's explicit row indices when it is a RowSparseNDArray
    (None otherwise) — the trigger for lazy row-sparse update kernels.

    Indices are padded to the next power-of-two length (by repeating the
    first index, which is harmless for the .set-based kernels: duplicate
    rows write the identical value) so the jitted update compiles per
    size *bucket*, not per distinct nonzero count.
    """
    from ..ndarray.sparse import RowSparseNDArray

    if not isinstance(grad, RowSparseNDArray):
        return None
    idx = grad.indices
    n = idx.shape[0]
    if n == 0:
        # sparse grad with zero stored rows: the lazy-update contract says
        # untouched rows stay bit-identical, so the whole update is a no-op
        # (only the update count advances) — falling back to the dense
        # kernel would wd-decay and momentum-integrate every row
        return _NO_ROWS
    cap = grad.shape[0]
    bucket = 1
    while bucket < n:
        bucket *= 2
    bucket = min(bucket, cap)
    if bucket == n:
        return idx
    raw = idx.asnumpy()
    padded = np.concatenate([raw, np.full(bucket - n, raw[0],
                                          raw.dtype)])
    return nd.array(padded, dtype="int64")

__all__ = ["Optimizer", "SGD", "Signum", "SignSGD", "FTML", "DCASGD", "NAG",
           "SGLD", "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax",
           "Nadam", "AdamW", "LBSGD", "LAMB", "Test", "Updater", "get_updater",
           "create", "register"]


def _is_low_precision(dtype):
    """True for dtypes that want an fp32 master copy (fp16 on GPU in the
    reference; bf16 is the TPU-native training dtype and gets the same
    multi-precision treatment)."""
    if np.dtype(dtype) == np.float16:
        return True
    try:
        import ml_dtypes
        return np.dtype(dtype) == np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover
        return False


class Optimizer:
    """Base optimizer (public surface of the reference ``optimizer.py``
    Optimizer class; internals are repo-idiom).

    Tracks per-parameter update counts (for time-dependent rules), lr/wd
    multipliers resolved from parameter attributes, and optional fp16/bf16
    multi-precision master weights.  ``aggregate_num > 0`` (a class
    attribute subclasses may set) tells the Updater this optimizer can
    batch that many parameters into one fused multi-tensor update call.
    """

    opt_registry: dict = {}
    aggregate_num = 0

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.rescale_grad, self.wd = rescale_grad, wd
        self.lr, self.lr_scheduler = learning_rate, lr_scheduler
        if lr_scheduler is not None:
            lr_scheduler.base_lr = learning_rate
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.num_update = self.begin_num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name)
        self.sym_info = ((sym.attr_dict(), sym.list_arguments())
                         if sym is not None else ())
        self.param_dict = dict(param_dict) if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # -- registry --------------------------------------------------------
    @staticmethod
    def register(klass):
        assert isinstance(klass, type)
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            logging.warning("New optimizer %s is overriding existing "
                            "optimizer %s", klass.__name__, name)
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    # -- state -----------------------------------------------------------
    def create_state(self, index, weight):
        """Create optimizer state (momentum etc.) for one parameter."""
        return None

    def _wants_master_copy(self, weight):
        low = _is_low_precision(weight.dtype)
        if low and not self.multi_precision:
            logging.warning("Accumulating with float16 in optimizer can lead "
                            "to poor accuracy or slow convergence. Consider "
                            "using multi_precision=True option.")
        return low and self.multi_precision

    def create_state_multi_precision(self, index, weight):
        """State plus fp32 master copy for low-precision weights; the
        master copy rides in the state tuple (reference convention:
        ``(state, weight32)``)."""
        if not self._wants_master_copy(weight):
            return self.create_state(index, weight)
        master = weight.astype(np.float32)
        return (self.create_state(index, master), master)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if not (self.multi_precision and _is_low_precision(weight.dtype)):
            self.update(index, weight, grad, state)
            return
        inner_state, master = state
        self.update(index, master, grad.astype(np.float32), inner_state)
        weight._set_data(master.astype(weight.dtype).data)

    # -- lr / wd resolution ----------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def _mults_from_sym(self, attr_key):
        """Per-arg-name multipliers declared as symbol attributes
        (``__lr_mult__``/``__wd_mult__``, reference attr convention)."""
        if not self.sym_info:
            return {}
        attrs, arg_names = self.sym_info
        return {n: float(attrs[n][attr_key]) for n in arg_names
                if attr_key in attrs.get(n, ())}

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {**self._mults_from_sym("__lr_mult__"),
                        **args_lr_mult}

    def set_wd_mult(self, args_wd_mult):
        # non-weight params (biases, norm gammas/betas, ...) default to
        # no weight decay, identified by name suffix like the reference
        no_decay = {n: 0.0 for n in self.idx2name.values()
                    if not n.endswith("_weight")}
        self.wd_mult = {**no_decay, **self._mults_from_sym("__wd_mult__"),
                        **args_wd_mult}

    def _update_count(self, index):
        for idx in index if isinstance(index, (list, tuple)) else (index,):
            count = self._index_update_count.get(
                idx, self.begin_num_update) + 1
            self._index_update_count[idx] = count
            if count > self.num_update:
                self.num_update = count

    def _mult_of(self, index, table, attr):
        """Multiplier for one param: Parameter attribute wins, then an
        entry keyed by index, then one keyed by the param's name."""
        if index in self.param_dict:
            return getattr(self.param_dict[index], attr)
        if index in table:
            return table[index]
        name = self.idx2name.get(index)
        return table.get(name, 1.0) if name is not None else 1.0

    def _get_lr(self, index):
        base = (self.lr_scheduler(self.num_update)
                if self.lr_scheduler is not None else self.lr)
        return base * self._mult_of(index, self.lr_mult, "lr_mult")

    def _get_wd(self, index):
        return self.wd * self._mult_of(index, self.wd_mult, "wd_mult")

    def __getstate__(self):
        # param_dict holds live Parameter objects — never pickled; the
        # loader re-attaches it (Trainer.load_states)
        return {k: v for k, v in self.__dict__.items()
                if k != "param_dict"}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.param_dict = {}

    # -- op dispatch helper ----------------------------------------------
    def _common_kwargs(self, index):
        kwargs = {"lr": self._get_lr(index), "wd": self._get_wd(index),
                  "rescale_grad": self.rescale_grad}
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient
        return kwargs

    def _begin_update(self, index):
        """Bump the update counter and hand back the shared op kwargs —
        the preamble every fused-update dispatch shares."""
        self._update_count(index)
        return self._common_kwargs(index)

    def _step_of(self, index):
        return self._index_update_count[index]


register = Optimizer.register  # pylint: disable=invalid-name


def _state_buf(weight):
    """A zero state buffer matching one weight (momentum, moments...)."""
    return zeros(weight.shape, weight.context, dtype=weight.dtype)


@register
class SGD(Optimizer):
    """SGD with momentum and optional fp16/bf16 master weights
    (reference: optimizer.py:498, fused ops sgd_update/sgd_mom_update/
    mp_sgd_update; list-valued updates use the multi_sgd_* multi-tensor
    kernels from src/operator/optimizer_op.cc — one XLA dispatch updating
    every aggregated parameter)."""

    aggregate_num = int(os.environ.get("MXNET_OPTIMIZER_AGGREGATION_SIZE",
                                       "4"))

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lazy_update = momentum, lazy_update

    def create_state_multi_precision(self, index, weight):
        if not (self.multi_precision and _is_low_precision(weight.dtype)):
            return self.create_state(index, weight)
        master = weight.astype(np.float32)
        return (self.create_state(index, master), master)

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return _state_buf(weight)
        return None

    def _update_one(self, index, weight, grad, state, multi_precision):
        kwargs = self._common_kwargs(index)
        if multi_precision:
            mom, weight32 = state
            if mom is not None:
                invoke("mp_sgd_mom_update", [weight, grad, mom, weight32],
                       dict(momentum=self.momentum, **kwargs))
            else:
                invoke("mp_sgd_update", [weight, grad, weight32], kwargs)
            return
        idx = _row_sparse_indices(grad) if self.lazy_update else None
        if idx is _NO_ROWS:
            return
        if idx is not None:
            # lazy row-sparse update: only rows present in the gradient
            # are touched (reference optimizer_op.cc row_sparse kernels)
            if state is not None:
                invoke("_sparse_sgd_mom_update", [weight, grad, idx, state],
                       dict(momentum=self.momentum, **kwargs))
            else:
                invoke("_sparse_sgd_update", [weight, grad, idx], kwargs)
        elif state is not None:
            invoke("sgd_mom_update", [weight, grad, state],
                   dict(momentum=self.momentum, **kwargs))
        else:
            invoke("sgd_update", [weight, grad], kwargs)

    def _update_fused(self, indices, weights, grads, states,
                      multi_precision):
        """One multi-tensor kernel over the whole aggregated group."""
        params = dict(num_weights=len(indices),
                      lrs=tuple(self._get_lr(i) for i in indices),
                      wds=tuple(self._get_wd(i) for i in indices),
                      rescale_grad=self.rescale_grad)
        if self.clip_gradient:
            params["clip_gradient"] = self.clip_gradient
        use_mom = self.momentum > 0
        if use_mom:
            params["momentum"] = self.momentum
        inter = []
        if multi_precision:
            op = ("multi_mp_sgd_mom_update" if use_mom
                  else "multi_mp_sgd_update")
            for w, g, (mom, w32) in zip(weights, grads, states):
                inter += [w, g, mom, w32] if use_mom else [w, g, w32]
        else:
            op = "multi_sgd_mom_update" if use_mom else "multi_sgd_update"
            for w, g, s in zip(weights, grads, states):
                inter += [w, g, s] if use_mom else [w, g]
        invoke(op, inter, params, out=list(weights))

    def _update_impl(self, index, weight, grad, state,
                     multi_precision=False):
        if not isinstance(index, (list, tuple)):
            index, weight, grad, state = \
                [index], [weight], [grad], [state]
        self._update_count(index)
        dense = all(getattr(w, "stype", "default") == "default"
                    and getattr(g, "stype", "default") == "default"
                    for w, g in zip(weight, grad))
        if dense and len(index) > 1:
            self._update_fused(index, weight, grad, state, multi_precision)
            return
        for i, w, g, s in zip(index, weight, grad, state):
            self._update_one(i, w, g, s, multi_precision)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        w0 = weight[0] if isinstance(weight, (list, tuple)) else weight
        use_mp = self.multi_precision and _is_low_precision(w0.dtype)
        self._update_impl(index, weight, grad, state, multi_precision=use_mp)


@register
class Signum(Optimizer):
    """SignSGD with momentum (reference: optimizer.py:644)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return _state_buf(weight)
        return None

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        if state is not None:
            invoke("signum_update", [weight, grad, state],
                   dict(momentum=self.momentum, wd_lh=self.wd_lh, **kwargs))
        else:
            invoke("signsgd_update", [weight, grad], kwargs)


@register
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register
class FTML(Optimizer):
    """Follow-the-moving-leader (reference: optimizer.py:711)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (_state_buf(weight),  # d
                _state_buf(weight),  # v
                _state_buf(weight))  # z

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        t = self._step_of(index)
        clip = kwargs.pop("clip_gradient", None)
        d, v, z = state
        invoke("ftml_update", [weight, grad, d, v, z],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    t=float(t), clip_grad=clip if clip else -1.0, **kwargs))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py:962)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        return weight.copy()  # previous weight

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        invoke("dcasgd_update", [weight, grad, state],
               dict(lamda=self.lamda, **kwargs))


@register
class NAG(Optimizer):
    """Nesterov accelerated gradient (reference: optimizer.py:1018)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return _state_buf(weight)
        return None

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        if state is not None:
            invoke("nag_mom_update", [weight, grad, state],
                   dict(momentum=self.momentum, **kwargs))
        else:
            invoke("sgd_update", [weight, grad], kwargs)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py:1070)."""

    def update(self, index, weight, grad, state):
        invoke("sgld_update", [weight, grad], self._begin_update(index))


@register
class Adam(Optimizer):
    """Adam (reference: optimizer.py:1107, fused op adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (_state_buf(weight),
                _state_buf(weight))

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        t = self._step_of(index)
        # bias correction folded into lr (reference does the same)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        kwargs["lr"] = kwargs["lr"] * math.sqrt(coef2) / coef1
        mean, var = state
        idx = _row_sparse_indices(grad) if self.lazy_update else None
        if idx is _NO_ROWS:
            return
        if idx is not None:
            invoke("_sparse_adam_update", [weight, grad, idx, mean, var],
                   dict(beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon, **kwargs))
        else:
            invoke("adam_update", [weight, grad, mean, var],
                   dict(beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon, **kwargs))


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference: optimizer.py:1191)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _state_buf(weight)

    def update(self, index, weight, grad, state):
        invoke("adagrad_update", [weight, grad, state],
               dict(epsilon=self.float_stable_eps,
                    **self._begin_update(index)))


@register
class RMSProp(Optimizer):
    """RMSProp, plain (Hinton) or centered (Alex Graves) variant
    (reference: optimizer.py:1250)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.centered = gamma1, gamma2, centered
        self.epsilon, self.clip_weights = epsilon, clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_state_buf(weight),  # n
                    _state_buf(weight),  # g
                    _state_buf(weight))  # delta
        return _state_buf(weight)

    def update(self, index, weight, grad, state):
        kwargs = dict(gamma1=self.gamma1, epsilon=self.epsilon,
                      **self._begin_update(index))
        if self.clip_weights:
            kwargs["clip_weights"] = self.clip_weights
        if not self.centered:
            invoke("rmsprop_update", [weight, grad, state], kwargs)
        else:
            n, g, delta = state
            invoke("rmspropalex_update", [weight, grad, n, g, delta],
                   dict(gamma2=self.gamma2, **kwargs))


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference: optimizer.py:1328)."""

    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (_state_buf(weight),
                _state_buf(weight))

    def update(self, index, weight, grad, state):
        acc_g, acc_d = state
        kwargs = self._begin_update(index)
        kwargs.pop("lr")
        invoke("adadelta_update", [weight, grad, acc_g, acc_d],
               dict(lr=1.0, rho=self.rho, epsilon=self.epsilon, **kwargs))


@register
class Ftrl(Optimizer):
    """FTRL (reference: optimizer.py:1388)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (_state_buf(weight),  # z
                _state_buf(weight))  # n

    def update(self, index, weight, grad, state):
        z, n = state
        invoke("ftrl_update", [weight, grad, z, n],
               dict(lamda1=self.lamda1, beta=self.beta,
                    **self._begin_update(index)))


@register
class Adamax(Optimizer):
    """AdaMax (reference: optimizer.py:1464)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (_state_buf(weight),
                _state_buf(weight))

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        t = self._step_of(index)
        mean, var = state
        invoke("adamax_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, t=float(t),
                    **kwargs))


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference: optimizer.py:1521)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay, self.m_schedule = schedule_decay, 1.0

    def create_state(self, index, weight):
        return (_state_buf(weight),
                _state_buf(weight))

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        t = self._step_of(index)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mean, var = state
        invoke("nadam_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    t=float(t), m_schedule=self.m_schedule,
                    schedule_decay=self.schedule_decay,
                    **kwargs))
        self.m_schedule *= momentum_t


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference:
    src/operator/contrib/adamw.cc via contrib optimizer)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.eta = eta

    def create_state(self, index, weight):
        return (_state_buf(weight),
                _state_buf(weight))

    def update(self, index, weight, grad, state):
        mean, var = state
        invoke("adamw_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    eta=self.eta, **self._begin_update(index)))


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise adaptive rate
    (reference: optimizer.py:769; simplified to warmup+momentum SGD)."""

    def __init__(self, momentum=0.9, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch


@register
class LAMB(Optimizer):
    """LAMB large-batch optimizer (TPU-native addition — the standard choice
    for large-batch pretraining on pods)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (_state_buf(weight),
                _state_buf(weight))

    def update(self, index, weight, grad, state):
        kwargs = self._begin_update(index)
        t = self._step_of(index)
        mean, var = state
        invoke("lamb_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    t=float(t), bias_correction=self.bias_correction,
                    **kwargs))


@register
class Test(Optimizer):
    """Trivial optimizer for testing (reference: optimizer.py Test)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data((weight + grad * self.rescale_grad).data)
        state._set_data(weight.data)


create = Optimizer.create_optimizer  # pylint: disable=invalid-name


class Updater:
    """Applies an optimizer to (index, grad, weight) triples, owning state
    (public surface of the reference optimizer.py Updater).

    When the optimizer declares ``aggregate_num > 0``, list-valued calls
    are chunked into same-dtype groups of dense parameters and handed to
    the optimizer as lists, which the SGD family turns into one
    ``multi_sgd_*`` multi-tensor kernel per chunk — the TPU answer to
    per-small-param dispatch overhead."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0
        # chunk plan cache: Trainer._update calls with the same param
        # list every step, so the dense/dtype grouping walk is identical
        # — precompute it once per (indices, dtypes, stypes) key and
        # replay slices on later steps (per-cache-key dispatch planning)
        self._chunk_key = None
        self._chunk_plan = None

    def _state_of(self, index, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        elif not self.states_synced[index]:
            self.states[index] = self.sync_state_context(
                self.states[index], weight.context)
            self.states_synced[index] = True
        return self.states[index]

    def _aggregate_chunks(self, indices, grads, weights):
        """Yield (idx_list, grad_list, weight_list) chunks: runs of
        dense, same-dtype params up to aggregate_num long; sparse or
        odd-dtype params come through as singleton chunks."""
        cap = max(int(self.optimizer.aggregate_num), 1)
        run = []
        for i, g, w in zip(indices, grads, weights):
            dense = (getattr(w, "stype", "default") == "default"
                     and getattr(g, "stype", "default") == "default")
            if not dense:
                if run:
                    yield tuple(zip(*run))
                    run = []
                yield ([i], [g], [w])
                continue
            if run and (len(run) >= cap or run[-1][2].dtype != w.dtype):
                yield tuple(zip(*run))
                run = []
            run.append((i, g, w))
        if run:
            yield tuple(zip(*run))

    def _chunk_slices(self, indices, grads, weights):
        """Positions of each aggregate chunk, cached per call signature."""
        key = (tuple(indices),
               tuple(str(w.dtype) for w in weights),
               tuple(getattr(w, "stype", "default") for w in weights),
               tuple(getattr(g, "stype", "default") for g in grads),
               int(self.optimizer.aggregate_num))
        if key != self._chunk_key:
            pos = {id(w): p for p, w in enumerate(weights)}
            plan = []
            for _, _, ws in self._aggregate_chunks(indices, grads, weights):
                plan.append([pos[id(w)] for w in ws])
            self._chunk_key = key
            self._chunk_plan = plan
        return self._chunk_plan

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            index, grad, weight = [index], [grad], [weight]
        if self.aggregate_updates and len(index) > 1:
            for ps in self._chunk_slices(index, grad, weight):
                idxs = [index[p] for p in ps]
                ws = [weight[p] for p in ps]
                gs = [grad[p] for p in ps]
                states = [self._state_of(i, w) for i, w in zip(idxs, ws)]
                self.optimizer.update_multi_precision(idxs, ws, gs, states)
            return
        for i, g, w in zip(index, grad, weight):
            self.optimizer.update_multi_precision(
                i, w, g, self._state_of(i, w))

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            return type(state)(
                self.sync_state_context(i, context) for i in state)
        return state

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return type(s)(to_np(i) for i in s)
            return s
        states = {k: to_np(v) for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer) if dump_optimizer
                            else states)


def get_updater(optimizer):
    """Wrap an optimizer into an updater callable (reference: get_updater)."""
    return Updater(optimizer)
