"""Optimizers: frontend classes dispatching to fused update ops.

Reference parity: ``python/mxnet/optimizer/optimizer.py`` (17 @register
classes, SGD:498 ... Nadam:1521, Updater:1608 with fp16 master weights) over
``src/operator/optimizer_op.cc`` fused kernels.  TPU-native: every
``update()`` invokes one registered jit'd update op
(``mxnet_tpu/ops/optimizer_ops.py``); ``lr``/``wd``/step counters are traced
scalars so schedules never trigger recompilation.
"""
from __future__ import annotations

import logging
import math
import pickle

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray, zeros
from ..ops.registry import invoke


_NO_ROWS = object()  # sentinel: row-sparse grad storing zero rows


def _row_sparse_indices(grad):
    """The gradient's explicit row indices when it is a RowSparseNDArray
    (None otherwise) — the trigger for lazy row-sparse update kernels.

    Indices are padded to the next power-of-two length (by repeating the
    first index, which is harmless for the .set-based kernels: duplicate
    rows write the identical value) so the jitted update compiles per
    size *bucket*, not per distinct nonzero count.
    """
    from ..ndarray.sparse import RowSparseNDArray

    if not isinstance(grad, RowSparseNDArray):
        return None
    idx = grad.indices
    n = idx.shape[0]
    if n == 0:
        # sparse grad with zero stored rows: the lazy-update contract says
        # untouched rows stay bit-identical, so the whole update is a no-op
        # (only the update count advances) — falling back to the dense
        # kernel would wd-decay and momentum-integrate every row
        return _NO_ROWS
    cap = grad.shape[0]
    bucket = 1
    while bucket < n:
        bucket *= 2
    bucket = min(bucket, cap)
    if bucket == n:
        return idx
    raw = idx.asnumpy()
    padded = np.concatenate([raw, np.full(bucket - n, raw[0],
                                          raw.dtype)])
    return nd.array(padded, dtype="int64")

__all__ = ["Optimizer", "SGD", "Signum", "SignSGD", "FTML", "DCASGD", "NAG",
           "SGLD", "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax",
           "Nadam", "AdamW", "LBSGD", "LAMB", "Test", "Updater", "get_updater",
           "create", "register"]


def _is_low_precision(dtype):
    """True for dtypes that want an fp32 master copy (fp16 on GPU in the
    reference; bf16 is the TPU-native training dtype and gets the same
    multi-precision treatment)."""
    if np.dtype(dtype) == np.float16:
        return True
    try:
        import ml_dtypes
        return np.dtype(dtype) == np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover
        return False


class Optimizer:
    """Base optimizer (reference: ``optimizer.py`` class Optimizer).

    Tracks per-parameter update counts (for time-dependent rules), lr/wd
    multipliers resolved from parameter attributes, and optional fp16
    multi-precision master weights.
    """

    opt_registry: dict = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None else ()
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # -- registry --------------------------------------------------------
    @staticmethod
    def register(klass):
        assert isinstance(klass, type)
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            logging.warning("New optimizer %s is overriding existing "
                            "optimizer %s", klass.__name__, name)
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    # -- state -----------------------------------------------------------
    def create_state(self, index, weight):
        """Create optimizer state (momentum etc.) for one parameter."""
        return None

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and _is_low_precision(weight.dtype):
            weight_master_copy = weight.astype(np.float32)
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        if _is_low_precision(weight.dtype) and not self.multi_precision:
            logging.warning("Accumulating with float16 in optimizer can lead "
                            "to poor accuracy or slow convergence. Consider "
                            "using multi_precision=True option.")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_low_precision(weight.dtype):
            original_state, weight_master_copy = state
            grad32 = grad.astype(np.float32)
            self.update(index, weight_master_copy, grad32, original_state)
            weight._set_data(weight_master_copy.astype(weight.dtype).data)
        else:
            self.update(index, weight, grad, state)

    # -- lr / wd resolution ----------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def __getstate__(self):
        ret = self.__dict__.copy()
        del ret["param_dict"]
        return ret

    def __setstate__(self, state):
        self.__dict__ = state
        self.param_dict = {}

    # -- op dispatch helper ----------------------------------------------
    def _common_kwargs(self, index):
        kwargs = {"lr": self._get_lr(index), "wd": self._get_wd(index),
                  "rescale_grad": self.rescale_grad}
        if self.clip_gradient:
            kwargs["clip_gradient"] = self.clip_gradient
        return kwargs


register = Optimizer.register  # pylint: disable=invalid-name


@register
class SGD(Optimizer):
    """SGD with momentum and optional fp16 master weights
    (reference: optimizer.py:498, fused ops sgd_update/sgd_mom_update/
    mp_sgd_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and _is_low_precision(weight.dtype):
            weight_master_copy = weight.astype(np.float32)
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        return self.create_state(index, weight)

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, weight.context, dtype=weight.dtype)
        return None

    def _update_impl(self, index, weight, grad, state, multi_precision=False):
        self._update_count(index)
        kwargs = self._common_kwargs(index)
        if not multi_precision:
            idx = _row_sparse_indices(grad) if self.lazy_update else None
            if idx is _NO_ROWS:
                return
            if idx is not None:
                # lazy row-sparse update: only rows present in the
                # gradient are touched (reference optimizer_op.cc
                # row_sparse sgd kernels)
                if state is not None:
                    invoke("_sparse_sgd_mom_update",
                           [weight, grad, idx, state],
                           dict(momentum=self.momentum, **kwargs))
                else:
                    invoke("_sparse_sgd_update", [weight, grad, idx],
                           kwargs)
            elif state is not None:
                invoke("sgd_mom_update", [weight, grad, state],
                       dict(momentum=self.momentum, **kwargs))
            else:
                invoke("sgd_update", [weight, grad], kwargs)
        else:
            mom, weight32 = state
            if mom is not None:
                invoke("mp_sgd_mom_update", [weight, grad, mom, weight32],
                       dict(momentum=self.momentum, **kwargs))
            else:
                invoke("mp_sgd_update", [weight, grad, weight32], kwargs)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and _is_low_precision(weight.dtype)
        self._update_impl(index, weight, grad, state, multi_precision=use_mp)


@register
class Signum(Optimizer):
    """SignSGD with momentum (reference: optimizer.py:644)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, weight.context, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kwargs = self._common_kwargs(index)
        if state is not None:
            invoke("signum_update", [weight, grad, state],
                   dict(momentum=self.momentum, wd_lh=self.wd_lh, **kwargs))
        else:
            invoke("signsgd_update", [weight, grad], kwargs)


@register
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register
class FTML(Optimizer):
    """Follow-the-moving-leader (reference: optimizer.py:711)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),  # d
                zeros(weight.shape, weight.context, dtype=weight.dtype),  # v
                zeros(weight.shape, weight.context, dtype=weight.dtype))  # z

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kwargs = self._common_kwargs(index)
        clip = kwargs.pop("clip_gradient", None)
        d, v, z = state
        invoke("ftml_update", [weight, grad, d, v, z],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    t=float(t), clip_grad=clip if clip else -1.0, **kwargs))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py:962)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        return weight.copy()  # previous weight

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kwargs = self._common_kwargs(index)
        invoke("dcasgd_update", [weight, grad, state],
               dict(lamda=self.lamda, **kwargs))


@register
class NAG(Optimizer):
    """Nesterov accelerated gradient (reference: optimizer.py:1018)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, weight.context, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kwargs = self._common_kwargs(index)
        if state is not None:
            invoke("nag_mom_update", [weight, grad, state],
                   dict(momentum=self.momentum, **kwargs))
        else:
            invoke("sgd_update", [weight, grad], kwargs)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py:1070)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        invoke("sgld_update", [weight, grad], self._common_kwargs(index))


@register
class Adam(Optimizer):
    """Adam (reference: optimizer.py:1107, fused op adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kwargs = self._common_kwargs(index)
        # bias correction folded into lr (reference does the same)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        kwargs["lr"] = kwargs["lr"] * math.sqrt(coef2) / coef1
        mean, var = state
        idx = _row_sparse_indices(grad) if self.lazy_update else None
        if idx is _NO_ROWS:
            return
        if idx is not None:
            invoke("_sparse_adam_update", [weight, grad, idx, mean, var],
                   dict(beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon, **kwargs))
        else:
            invoke("adam_update", [weight, grad, mean, var],
                   dict(beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon, **kwargs))


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference: optimizer.py:1191)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        invoke("adagrad_update", [weight, grad, state],
               dict(epsilon=self.float_stable_eps,
                    **self._common_kwargs(index)))


@register
class RMSProp(Optimizer):
    """RMSProp, plain (Hinton) or centered (Alex Graves) variant
    (reference: optimizer.py:1250)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context, dtype=weight.dtype),  # n
                    zeros(weight.shape, weight.context, dtype=weight.dtype),  # g
                    zeros(weight.shape, weight.context, dtype=weight.dtype))  # delta
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kwargs = dict(gamma1=self.gamma1, epsilon=self.epsilon,
                      **self._common_kwargs(index))
        if self.clip_weights:
            kwargs["clip_weights"] = self.clip_weights
        if not self.centered:
            invoke("rmsprop_update", [weight, grad, state], kwargs)
        else:
            n, g, delta = state
            invoke("rmspropalex_update", [weight, grad, n, g, delta],
                   dict(gamma2=self.gamma2, **kwargs))


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference: optimizer.py:1328)."""

    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        acc_g, acc_d = state
        kwargs = self._common_kwargs(index)
        kwargs.pop("lr")
        invoke("adadelta_update", [weight, grad, acc_g, acc_d],
               dict(lr=1.0, rho=self.rho, epsilon=self.epsilon, **kwargs))


@register
class Ftrl(Optimizer):
    """FTRL (reference: optimizer.py:1388)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),  # z
                zeros(weight.shape, weight.context, dtype=weight.dtype))  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        invoke("ftrl_update", [weight, grad, z, n],
               dict(lamda1=self.lamda1, beta=self.beta,
                    **self._common_kwargs(index)))


@register
class Adamax(Optimizer):
    """AdaMax (reference: optimizer.py:1464)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        invoke("adamax_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, t=float(t),
                    **self._common_kwargs(index)))


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference: optimizer.py:1521)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mean, var = state
        invoke("nadam_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    t=float(t), m_schedule=self.m_schedule,
                    schedule_decay=self.schedule_decay,
                    **self._common_kwargs(index)))
        self.m_schedule *= momentum_t


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference:
    src/operator/contrib/adamw.cc via contrib optimizer)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.eta = eta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        mean, var = state
        invoke("adamw_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    eta=self.eta, **self._common_kwargs(index)))


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise adaptive rate
    (reference: optimizer.py:769; simplified to warmup+momentum SGD)."""

    def __init__(self, momentum=0.9, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch


@register
class LAMB(Optimizer):
    """LAMB large-batch optimizer (TPU-native addition — the standard choice
    for large-batch pretraining on pods)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        invoke("lamb_update", [weight, grad, mean, var],
               dict(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                    t=float(t), bias_correction=self.bias_correction,
                    **self._common_kwargs(index)))


@register
class Test(Optimizer):
    """Trivial optimizer for testing (reference: optimizer.py Test)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data((weight + grad * self.rescale_grad).data)
        state._set_data(weight.data)


create = Optimizer.create_optimizer  # pylint: disable=invalid-name


class Updater:
    """Applies an optimizer to (index, grad, weight) triples, owning state
    (reference: optimizer.py:1608; fp16 master weights in states)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = False

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices, grads, weights = [index], [grad], [weight]
        else:
            indices, grads, weights = index, grad, weight
        for i, g, w in zip(indices, grads, weights):
            if i not in self.states:
                self.states[i] = self.optimizer.create_state_multi_precision(i, w)
                self.states_synced[i] = True
            elif not self.states_synced[i]:
                self.states[i] = self.sync_state_context(self.states[i], w.context)
                self.states_synced[i] = True
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            return type(state)(
                self.sync_state_context(i, context) for i in state)
        return state

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return type(s)(to_np(i) for i in s)
            return s
        states = {k: to_np(v) for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer) if dump_optimizer
                            else states)


def get_updater(optimizer):
    """Wrap an optimizer into an updater callable (reference: get_updater)."""
    return Updater(optimizer)
