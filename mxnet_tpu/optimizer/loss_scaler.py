"""Dynamic loss scaling (the reference's ``contrib.amp`` LossScaler:
``python/mxnet/amp/loss_scaler.py`` grow/backoff automaton, reimplemented
for the fused TPU step).

The scale multiplies the loss *inside* the compiled train step (it rides
the per-step traced scalar vector, so changing it never recompiles) and
its reciprocal is folded into ``rescale_grad`` on the host — the applied
update is mathematically identical to unscaled training whenever the
gradients stay finite, while small bf16/fp16 gradients are lifted out of
the flush-to-zero band.

The automaton is the standard one: a non-finite step multiplies the
scale by ``backoff_factor`` (the step itself is skipped by the sentinel);
``growth_interval`` consecutive finite steps multiply it by
``growth_factor``.  See docs/NUMERICAL_HEALTH.md.
"""
from __future__ import annotations

__all__ = ["DynamicLossScaler"]


class DynamicLossScaler:
    """Grow/backoff loss-scale automaton.

    Parameters mirror the reference AMP defaults: ``init_scale`` 2**16,
    halve on overflow, double every ``growth_interval`` clean steps,
    clamped to [``min_scale``, ``max_scale``].  ``init_scale=1.0`` makes
    the scaler a no-op until the first overflow (the mode the sentinel
    uses when the user did not opt into mixed-precision scaling).
    """

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000,
                 min_scale=1.0, max_scale=2.0 ** 24):
        if not (0.0 < backoff_factor < 1.0):
            raise ValueError("backoff_factor must be in (0, 1)")
        if growth_factor <= 1.0:
            raise ValueError("growth_factor must be > 1")
        self.loss_scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.min_scale = float(min_scale)
        self.max_scale = float(max_scale)
        self._unskipped = 0

    def update(self, found_inf):
        """Advance the automaton one step; returns the scale to use for
        the NEXT step.  ``found_inf`` is this step's sentinel verdict."""
        if found_inf:
            return self.backoff()
        self._unskipped += 1
        if self._unskipped >= self.growth_interval:
            self._unskipped = 0
            self.loss_scale = min(self.max_scale,
                                  self.loss_scale * self.growth_factor)
        return self.loss_scale

    def backoff(self):
        """Overflow response: shrink the scale, restart the growth
        clock.  Idempotent at ``min_scale`` (returns False from
        :meth:`can_backoff` there so the escalation ladder advances)."""
        self._unskipped = 0
        self.loss_scale = max(self.min_scale,
                              self.loss_scale * self.backoff_factor)
        return self.loss_scale

    def can_backoff(self):
        return self.loss_scale > self.min_scale

    # -- checkpointing ----------------------------------------------------
    def state_dict(self):
        return {"loss_scale": self.loss_scale,
                "unskipped": self._unskipped}

    def load_state_dict(self, state):
        self.loss_scale = float(state["loss_scale"])
        self._unskipped = int(state["unskipped"])

    def __repr__(self):
        return ("DynamicLossScaler(scale=%g, unskipped=%d)"
                % (self.loss_scale, self._unskipped))
