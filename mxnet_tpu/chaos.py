"""Deterministic seeded fault injection (the chaos harness).

PR 2's elastic layer proved the framework survives *external* faults
(crash, SIGTERM, torn checkpoint) via one-off ``MXTPU_FI_*`` hooks; this
module generalizes them into one declarative, seeded plan so the chaos
suite (``tests/test_chaos.py``, ``ci/runtime_functions.sh chaos_check``)
can exercise the numerical-health sentinel AND the resilience paths
end-to-end — and so a failure reproduces from nothing but the spec
string.

A plan is a comma list of ``fault@step`` items plus an optional seed::

    MXNET_CHAOS="seed=7,nan_grad@3,kv_drop@5"        # env-driven
    with chaos.inject("nan_grad@3", seed=7): ...      # scoped

Faults (each firing bumps the ``faults_injected`` dispatch counter):

==================  ========================================================
``nan_grad@N``      poison step N's loss scale with NaN so every gradient
                    goes non-finite through the *genuine* backward path
                    (``FusedTrainStep`` hook; no recompile — the scale is
                    a traced scalar input)
``bitflip_param@N`` flip one seeded bit of one parameter element at the
                    step-N boundary (host-side SDC model, Dixit et al.)
``kv_drop@N``       the async-KV client's Nth call loses its reply after
                    send (exercises retransmit + server dedup)
``kv_delay@N``      delay the Nth call before send (reordering window)
``kv_dup@N``        transmit the Nth call twice (server must dedup)
``ckpt_truncate@N`` truncate checkpoint step N's params file mid-write
                    (via :func:`corrupt_checkpoint`)
``ckpt_bitflip@N``  flip one seeded bit in checkpoint step N's params file
``loader_raise@N``  ``ChaosDataset`` raises on its Nth record fetch
``slow_replica@N``  serving: the Nth model execution stalls ~250ms before
                    running (straggler — exercises hedging/deadlines)
``replica_crash@N`` serving: the Nth model execution raises
                    :class:`InjectedReplicaCrash` (exercises failover +
                    circuit breaker)
``request_burst@N`` serving: the Nth load-generator wave is multiplied
                    8x (overload — exercises shedding/bounded queue)
``registry_stale@N``  fleet: the Nth heartbeat publish is dropped so the
                    registry entry's TTL lapses and the reaper fires
                    (docs/SHARDED_SERVING.md)
``replica_slow_start@N``  fleet: the Nth ``ModelServer.add_replica`` call
                    stalls ~300ms before building (a cold replica whose
                    compile/weight load drags — the autoscaler must
                    absorb it, not wedge)
``gateway_partition@N``  gateway: the Nth registry refresh fails as if the
                    KV registry were unreachable — the gateway must keep
                    routing from its last-known-good ``FleetView`` with
                    staleness marking and re-sync on heal
                    (docs/SHARDED_SERVING.md "Deployment")
``worker_kill@N``   fleet: the Nth worker-kill opportunity SIGKILLs a
                    live worker process mid-stream — the supervisor must
                    restart it and the gateway must give every admitted
                    request exactly one typed terminal outcome
``worker_kill_mid_decode@N``  fleet: like ``worker_kill`` but the Nth
                    opportunity only fires once at least one generation
                    stream has streamed >= 1 token — the gateway must
                    resume the stream on a sibling from its journal
                    (exactly-once token delivery, docs/SHARDED_SERVING.md)
``page_pressure@N``  generation: the Nth scheduler-loop opportunity
                    impounds most of the KV free list for a bounded
                    window — page exhaustion that must preempt the
                    lowest-priority stream, never shed a higher one
``migrate_interrupt@N``  gateway: the Nth KV-migration chunk push is
                    killed mid-transfer — the gateway must abort the
                    receiver (freeing its pages via the leak-audited
                    contract) and degrade to the resume-from-journal
                    path, so the client still sees exactly one typed
                    outcome (docs/SHARDED_SERVING.md "Live migration")
``drain_migrate@N``  fleet: the Nth drain-migrate opportunity with at
                    least one active generation stream SIGTERMs a live
                    worker (rc-76 drain, not a crash) — the worker must
                    park + export its streams so the gateway re-attaches
                    them on siblings with zero ``ReplicaLost`` and zero
                    re-prefills
==================  ========================================================

Every fault fires at most once per process (deterministic, idempotent
under retry loops); ``step`` counts are 0-based and fault-local (the Nth
opportunity of that kind).  The plan is inert — ``maybe(...)`` costs one
attribute load — unless ``MXNET_CHAOS`` is set or ``inject()`` is active.
"""
from __future__ import annotations

import os
import threading

import numpy as np

__all__ = ["ChaosPlan", "ChaosDataset", "inject", "active",
           "corrupt_loss_scale", "poison_grad", "flip_param_bit",
           "arm_kv_client", "corrupt_checkpoint", "FAULT_KINDS",
           "slow_replica", "replica_crash", "request_burst",
           "registry_stale", "replica_slow_start",
           "gateway_partition", "worker_kill",
           "worker_kill_mid_decode", "page_pressure",
           "migrate_interrupt", "drain_migrate",
           "tenant_flood", "adapter_swap_mid_burst",
           "InjectedReplicaCrash"]

FAULT_KINDS = frozenset({
    "nan_grad", "bitflip_param", "kv_drop", "kv_delay", "kv_dup",
    "ckpt_truncate", "ckpt_bitflip", "loader_raise",
    "slow_replica", "replica_crash", "request_burst",
    "registry_stale", "replica_slow_start",
    "gateway_partition", "worker_kill",
    "worker_kill_mid_decode", "page_pressure",
    "migrate_interrupt", "drain_migrate",
    "tenant_flood", "adapter_swap_mid_burst",
})


class InjectedReplicaCrash(RuntimeError):
    """The failure :func:`replica_crash` raises inside a serving replica
    execution — caught by the serving layer's failover path like any
    real replica fault."""


def _count_fault():
    from . import profiler as _prof

    _prof.dispatch_count("faults_injected")


class ChaosPlan:
    """Parsed, seeded fault plan.  ``fire(kind, step)`` is True exactly
    once for each ``kind@step`` item in the spec (and then consumed), so
    injected faults stay deterministic under restarts and retries."""

    def __init__(self, spec, seed=0):
        self.spec = spec
        self.seed = int(seed)
        self._faults = {}      # (kind, step) -> not-yet-fired
        self._lock = threading.Lock()
        for item in str(spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            if item.startswith("seed="):
                self.seed = int(item[len("seed="):])
                continue
            if "@" not in item:
                raise ValueError("MXNET_CHAOS item %r: expected "
                                 "'fault@step' or 'seed=N'" % item)
            kind, step = item.split("@", 1)
            kind = kind.strip()
            if kind not in FAULT_KINDS:
                raise ValueError("MXNET_CHAOS: unknown fault %r (one of "
                                 "%s)" % (kind, sorted(FAULT_KINDS)))
            self._faults[(kind, int(step))] = True
        self.kinds = frozenset(k for k, _ in self._faults)

    def rng(self, kind, step):
        """Per-fault deterministic RNG: which bit/element gets hit
        depends only on (seed, kind, step), never on call order."""
        return np.random.RandomState(
            (self.seed * 1000003 + hash((kind, step))) & 0x7FFFFFFF)

    def fire(self, kind, step):
        """True exactly once when the plan schedules ``kind`` at this
        fault-local ``step``; bumps ``faults_injected``."""
        if kind not in self.kinds:
            return False
        with self._lock:
            if not self._faults.get((kind, int(step))):
                return False
            self._faults[(kind, int(step))] = False
        _count_fault()
        return True

    def pending(self):
        """Faults not yet fired (chaos tests assert this drains empty)."""
        return sorted(k for k, live in self._faults.items() if live)


_scoped = None
_env_plan = None
_env_spec_seen = None


def active():
    """The active :class:`ChaosPlan`, or None.  A scoped ``inject()``
    shadows the ``MXNET_CHAOS`` env plan."""
    global _env_plan, _env_spec_seen
    if _scoped is not None:
        return _scoped
    spec = os.environ.get("MXNET_CHAOS", "")
    if not spec:
        return None
    if spec != _env_spec_seen:  # re-parse only when the env changes
        _env_plan = ChaosPlan(spec)
        _env_spec_seen = spec
    return _env_plan


class inject:
    """Context manager arming a scoped chaos plan::

        with chaos.inject("nan_grad@2", seed=7) as plan:
            train()
        assert plan.pending() == []
    """

    def __init__(self, spec, seed=0):
        self.plan = ChaosPlan(spec, seed=seed)

    def __enter__(self):
        global _scoped
        if _scoped is not None:
            raise RuntimeError("chaos.inject() does not nest")
        _scoped = self.plan
        return self.plan

    def __exit__(self, *exc):
        global _scoped
        _scoped = None
        return False


# ---------------------------------------------------------------------------
# hot-path hooks (each is a cheap no-op without an active plan)
# ---------------------------------------------------------------------------
def corrupt_loss_scale(step, scale):
    """``nan_grad``: return NaN in place of the step's loss scale.  The
    scale multiplies the loss inside the compiled step, so every gradient
    goes non-finite through the real backward computation — the exact
    signature a hardware SDC or fp16 overflow produces."""
    plan = active()
    if plan is not None and plan.fire("nan_grad", step):
        return float("nan")
    return scale


def poison_grad(step, params):
    """``nan_grad`` on the eager Trainer path (which has no loss-scale
    slot): write NaN into one seeded element of one parameter's gradient
    before the update.  Returns the poisoned parameter's name or None."""
    plan = active()
    if plan is None or not params or "nan_grad" not in plan.kinds:
        return None
    if not plan.fire("nan_grad", step):
        return None
    rng = plan.rng("nan_grad", step)
    p = params[rng.randint(len(params))]
    for g in p.list_grad():
        host = np.array(g.asnumpy())   # asnumpy views are read-only
        flat = host.reshape(-1)
        if flat.size == 0 or flat.dtype.kind != "f":
            return None
        flat[rng.randint(flat.size)] = np.nan
        import jax.numpy as jnp

        g._set_data(jnp.asarray(host, dtype=g.data.dtype))
    return getattr(p, "name", None)


def flip_param_bit(step, params):
    """``bitflip_param``: flip one seeded bit of one element of one
    parameter (host-side write-back).  Returns the poisoned parameter's
    name, or None when nothing fired."""
    plan = active()
    if plan is None or not params or "bitflip_param" not in plan.kinds:
        return None
    if not plan.fire("bitflip_param", step):
        return None
    rng = plan.rng("bitflip_param", step)
    p = params[rng.randint(len(params))]
    arr = p.list_data()[0] if hasattr(p, "list_data") else p
    host = np.array(arr.asnumpy())     # asnumpy views are read-only
    flat = host.reshape(-1)
    if flat.size == 0 or flat.dtype.kind not in "fiu":
        return None
    idx = rng.randint(flat.size)
    bits = flat[idx:idx + 1].view("u%d" % flat.dtype.itemsize)
    bits ^= np.asarray(1, bits.dtype) << rng.randint(8 * flat.dtype.itemsize)
    import jax.numpy as jnp

    arr._set_data(jnp.asarray(host, dtype=arr.data.dtype))
    return getattr(p, "name", None)


def arm_kv_client(client):
    """Arm the async-KV transport faults (``kv_drop``/``kv_delay``/
    ``kv_dup``) on an :class:`~mxnet_tpu.async_kv.AsyncKVClient`.  The
    step number in the spec is the 1-based sequence number of the call to
    hit (the client numbers requests from 1)."""
    plan = active()
    if plan is None:
        return client
    for (kind, seq), live in list(plan._faults.items()):
        if not live:
            continue
        if kind == "kv_drop":
            client._fi_drop_after_send.add(seq)
        elif kind == "kv_delay":
            client._fi_delay_before_send[seq] = 0.05
        elif kind == "kv_dup":
            client._fi_duplicate_send.add(seq)
    return client


def note_kv_fault(kind, seq):
    """Called by the async_kv client when an armed transport fault
    actually fires — routes the event through the plan so counters and
    ``pending()`` stay truthful."""
    plan = active()
    if plan is not None:
        plan.fire(kind, seq)
    else:
        _count_fault()  # hand-armed via the test hooks


def corrupt_checkpoint(manager, step=None, mode="truncate"):
    """``ckpt_truncate`` / ``ckpt_bitflip``: damage a *committed*
    checkpoint's params file in place (newest by default) — the torn
    write / bit-rot the CRC meta exists to catch.  Returns the damaged
    step.  Usable directly from tests (no active plan required)."""
    steps = manager.steps()
    if not steps:
        raise ValueError("no committed checkpoint to corrupt")
    step = steps[-1] if step is None else step
    path = manager._params_path(step)
    plan = active()
    rng = plan.rng("ckpt_" + mode, step) if plan is not None \
        else np.random.RandomState(step)
    if plan is not None:
        plan.fire("ckpt_truncate" if mode == "truncate" else "ckpt_bitflip",
                  step)
    else:
        _count_fault()
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(0, size // 2))
    elif mode == "bitflip":
        off = int(rng.randint(max(1, size)))
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([(b[0] if b else 0) ^ (1 << int(rng.randint(8)))]))
    else:
        raise ValueError("mode must be 'truncate' or 'bitflip'")
    return step


# ---------------------------------------------------------------------------
# serving fault hooks (mxnet_tpu.serving worker loop / load generators)
# ---------------------------------------------------------------------------
def slow_replica(n, delay=0.25):
    """``slow_replica@N``: seconds the Nth model execution should stall
    before running (0.0 otherwise).  The serving worker sleeps OUTSIDE
    every lock, then executes normally — a straggler, not a failure."""
    plan = active()
    if plan is not None and plan.fire("slow_replica", n):
        return float(delay)
    return 0.0


def replica_crash(n):
    """``replica_crash@N``: raise :class:`InjectedReplicaCrash` in place
    of the Nth model execution — feeds the serving failover + circuit
    breaker exactly like a real replica fault."""
    plan = active()
    if plan is not None and plan.fire("replica_crash", n):
        raise InjectedReplicaCrash("chaos: injected crash on serving "
                                   "execution %d" % n)


def request_burst(n, factor=8):
    """``request_burst@N``: multiplier for the Nth load-generator wave
    (1 otherwise) — the traffic spike the bounded admission queue must
    shed, not absorb."""
    plan = active()
    if plan is not None and plan.fire("request_burst", n):
        return int(factor)
    return 1


def registry_stale(n):
    """``registry_stale@N``: True when the Nth fleet heartbeat publish
    should be dropped — the replica's TTL'd registry entry lapses and
    the reaper must purge it (then the next beat re-registers; the
    fleet view self-heals)."""
    plan = active()
    return plan is not None and plan.fire("registry_stale", n)


def replica_slow_start(n, delay=0.3):
    """``replica_slow_start@N``: seconds the Nth ``add_replica`` build
    should stall before starting (0.0 otherwise).  The serving layer
    sleeps OUTSIDE every lock, then builds normally — a slow cold
    start, not a failure; scale-up latency absorbs it."""
    plan = active()
    if plan is not None and plan.fire("replica_slow_start", n):
        return float(delay)
    return 0.0


def gateway_partition(n):
    """``gateway_partition@N``: True when the gateway's Nth registry
    refresh should fail as if the KV registry were unreachable.  The
    gateway keeps serving from its last-known-good :class:`FleetView`
    (marked stale) and re-syncs on the next successful refresh — the
    same self-healing contract :func:`registry_stale` proves for the
    worker side."""
    plan = active()
    return plan is not None and plan.fire("gateway_partition", n)


def worker_kill(n):
    """``worker_kill@N``: True when the Nth worker-kill opportunity
    should SIGKILL a live worker process (a hard crash, no drain).  The
    WorkerSupervisor must restart it within the backoff budget and the
    gateway must fail over — retrying idempotent work, resolving
    non-resumable streams with typed ``ReplicaLost``."""
    plan = active()
    return plan is not None and plan.fire("worker_kill", n)


def worker_kill_mid_decode(n, streamed):
    """``worker_kill_mid_decode@N``: True when the Nth opportunity should
    SIGKILL a live worker AND at least one generation stream has already
    streamed a token (``streamed >= 1``).  Unlike ``worker_kill`` this
    targets the mid-decode window specifically: the gateway must resume
    the interrupted stream on a sibling from its journal so the client
    sees an exactly-once continuation, not ``ReplicaLost``."""
    plan = active()
    if plan is None or streamed < 1:
        return False
    return plan.fire("worker_kill_mid_decode", n)


def migrate_interrupt(n):
    """``migrate_interrupt@N``: True when the Nth KV-migration chunk
    push should die mid-transfer (the gateway raises a connection error
    between chunks).  The transfer-abort path must free the receiver's
    partial buffer/pages (leakcheck-audited) and the stream must degrade
    to the resume-from-journal path — migration is never worse than
    failover, even when the transfer itself is the casualty."""
    plan = active()
    return plan is not None and plan.fire("migrate_interrupt", n)


def drain_migrate(n, streams):
    """``drain_migrate@N``: True when the Nth opportunity should SIGTERM
    a live worker that holds ``streams >= 1`` active generation streams
    — a *planned* drain (rc-76), not a crash.  The zero-loss drain
    contract: the worker parks + exports every active stream and the
    gateway re-attaches each on a sibling, so the chaos suite asserts
    zero ``ReplicaLost`` and zero re-prefills alongside the usual
    exactly-one-typed-outcome invariant."""
    plan = active()
    if plan is None or streams < 1:
        return False
    return plan.fire("drain_migrate", n)


def tenant_flood(n, factor=8):
    """``tenant_flood@N``: multiplier for the Nth load-generator wave's
    *single noisiest tenant* (1 otherwise) — one tenant suddenly offers
    ``factor``x its traffic while everyone else stays steady.  The
    noisy-neighbor isolation contract: the flooder sheds typed
    ``QuotaExceeded`` at its token-bucket/fair-share limits and every
    other tenant's TTFT p99 stays put (docs/SHARDED_SERVING.md
    "Multi-tenant serving")."""
    plan = active()
    if plan is not None and plan.fire("tenant_flood", n):
        return int(factor)
    return 1


def adapter_swap_mid_burst(n, adapters):
    """``adapter_swap_mid_burst@N``: True when the worker's Nth
    heartbeat should hot-swap a route's resident adapter while traffic
    is in flight — an operator rollout at the worst moment.  Gated on
    ``adapters >= 1`` resident adapters BEFORE consuming the plan item,
    so the fault waits for a swappable worker.  The atomic hot-swap
    contract absorbs it: in-flight streams keep their typed outcomes
    and the recompile counter does not move."""
    plan = active()
    if plan is None or adapters < 1:
        return False
    return plan.fire("adapter_swap_mid_burst", n)


def page_pressure(n, frac=0.9):
    """``page_pressure@N``: fraction of the KV free list the generation
    scheduler should impound on its Nth opportunity (0.0 otherwise).  The
    resulting page exhaustion must be absorbed by QoS preemption — the
    lowest-priority stream is journaled and re-admitted, never a
    higher-priority one shed (docs/GENERATIVE.md)."""
    plan = active()
    if plan is not None and plan.fire("page_pressure", n):
        return float(frac)
    return 0.0


class ChaosDataset:
    """Wrap a Dataset so fetch N raises (``loader_raise@N`` — the
    mid-batch reader failure the DataLoader's skip-and-count path must
    absorb).  Without an active plan it is transparent."""

    def __init__(self, dataset, error=IOError("chaos: injected record "
                                              "read failure")):
        self._dataset = dataset
        self._error = error
        self._fetches = 0
        self._lock = threading.Lock()

    def __len__(self):
        return len(self._dataset)

    def __getitem__(self, idx):
        plan = active()
        with self._lock:
            n = self._fetches
            self._fetches += 1
        if plan is not None and plan.fire("loader_raise", n):
            raise type(self._error)(*self._error.args)
        return self._dataset[idx]
