"""Device context management.

Capability parity with the reference's ``Context`` (``python/mxnet/context.py``,
``include/mxnet/base.h`` Context struct): a (dev_type, dev_id) pair with a
thread-local default and a ``with`` scope.  TPU-native design: a Context maps to a
``jax.Device`` (or, for sharded execution, a position in a ``jax.sharding.Mesh``);
there is no per-device stream/thread state here because XLA owns scheduling.
"""
from __future__ import annotations

import threading

import jax

_DEVTYPE_TO_ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
_ID_TO_DEVTYPE = {v: k for k, v in _DEVTYPE_TO_ID.items()}


class Context:
    """A device context, usable as a ``with`` scope to set the default device.

    Unlike the reference (CUDA device + stream), a TPU Context resolves lazily to a
    ``jax.Device``; ``gpu`` is accepted as an alias for the local accelerator so
    reference scripts run unmodified.
    """

    _default_ctx = threading.local()
    devtype2id = _DEVTYPE_TO_ID
    devid2type = _ID_TO_DEVTYPE

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type = device_type.device_type
            self.device_id = device_type.device_id
        else:
            if device_type not in _DEVTYPE_TO_ID:
                raise ValueError("unknown device type %r" % (device_type,))
            self.device_type = device_type
            self.device_id = device_id

    @property
    def device_typeid(self):
        return _DEVTYPE_TO_ID[self.device_type]

    # -- jax bridge ---------------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        ``tpu``/``gpu`` both mean "the local accelerator" (axon shows TPU); if no
        accelerator is present they fall back to host CPU so the same test corpus
        runs everywhere (mirrors the reference's context-generic test strategy,
        SURVEY.md §4).
        """
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                return jax.local_devices(backend="cpu")[self.device_id]
            except RuntimeError:
                return jax.local_devices()[0]
        devs = jax.local_devices()
        accel = [d for d in devs if d.platform != "cpu"]
        pool = accel if accel else devs
        return pool[self.device_id % len(pool)]

    # -- python protocol ----------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *args):
        Context._default_ctx.stack.pop()

    @classmethod
    def default_ctx(cls):
        stack = getattr(cls._default_ctx, "stack", None)
        if stack:
            return stack[-1]
        return _global_default()


def cpu(device_id=0):
    """Return a CPU context."""
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Alias for the local accelerator (parity shim: reference scripts say gpu)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context — the native accelerator of this framework."""
    return Context("tpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def num_gpus():
    """Number of local accelerator devices (TPU chips here)."""
    return len([d for d in jax.local_devices() if d.platform != "cpu"])


def num_tpus():
    return num_gpus()


def current_context():
    return Context.default_ctx()


_GLOBAL_DEFAULT = None


def _global_default():
    # Lazy: resolving devices initializes the jax backend, which we defer until
    # first use so that `import mxnet_tpu` stays cheap.
    global _GLOBAL_DEFAULT
    if _GLOBAL_DEFAULT is None:
        _GLOBAL_DEFAULT = Context("tpu", 0) if num_gpus() else Context("cpu", 0)
    return _GLOBAL_DEFAULT

