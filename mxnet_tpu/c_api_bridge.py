"""Python half of the native imperative C ABI (``native/c_api.cc``).

The reference routes every frontend through ``src/c_api/c_api.cc`` /
``c_api_ndarray.cc:118-235`` (``MXImperativeInvokeEx``): handles are C++
``NDArray*`` and hyper-parameters arrive as strings that the backend
parses against each op's ``dmlc::Parameter`` signature.  Here the roles
invert — the runtime is Python/XLA, so the embedded-C layer marshals
into *this* module: handles are ``mxnet_tpu.ndarray.NDArray`` objects
held by native code as ``PyObject*``, and this module does the
string->typed-param parsing the reference does with dmlc parameter
structs.
"""
from __future__ import annotations

import ast

import numpy as np

from . import context as _context
from .ndarray import ndarray as _nd
from .ndarray import utils as _nd_utils
from .ops import registry as _registry

# reference dtype codes: python/mxnet/base.py _DTYPE_MX_TO_NP; code 7 is
# the TPU-native bfloat16 extension (the reference era predates bf16).
_DTYPE_FROM_CODE = {
    0: "float32",
    1: "float64",
    2: "float16",
    3: "uint8",
    4: "int32",
    5: "int8",
    6: "int64",
    7: "bfloat16",
}
_CODE_FROM_DTYPE = {v: k for k, v in _DTYPE_FROM_CODE.items()}


def _ctx(dev_type, dev_id):
    return _context.cpu(dev_id) if dev_type == 1 else _context.tpu(dev_id)


def create(shape, dev_type, dev_id, dtype_code):
    dtype = _DTYPE_FROM_CODE.get(int(dtype_code))
    if dtype is None:
        raise ValueError("unknown dtype code %r" % (dtype_code,))
    return _nd.zeros(tuple(int(s) for s in shape),
                     ctx=_ctx(dev_type, dev_id), dtype=dtype)


def dtype_code(arr):
    name = np.dtype(arr.dtype).name if arr.dtype != "bfloat16" else "bfloat16"
    try:
        return _CODE_FROM_DTYPE[str(name)]
    except KeyError:
        raise TypeError("dtype %r has no ABI code" % (name,))


def context_of(arr):
    c = arr.context
    return (1 if c.device_type == "cpu" else 2), c.device_id


def copy_from_bytes(arr, buf):
    """Host->device: reinterpret ``buf`` in the array's dtype/shape."""
    if str(arr.dtype) == "bfloat16":
        import jax.numpy as jnp

        host = np.frombuffer(buf, dtype=np.uint16).view(jnp.bfloat16.dtype)
    else:
        host = np.frombuffer(buf, dtype=np.dtype(str(arr.dtype)))
    if host.size != arr.size:
        raise ValueError("copy size %d != array size %d"
                         % (host.size, arr.size))
    arr._set_data(
        _nd.array(host.reshape(arr.shape), ctx=arr.context,
                  dtype=arr.dtype).data)
    return arr


def to_bytes(arr):
    """Device->host: raw bytes in the array's dtype (sync point)."""
    host = arr.asnumpy()
    return np.ascontiguousarray(host).tobytes()


def element_bytes(arr):
    return np.dtype(str(arr.dtype)).itemsize if str(arr.dtype) != "bfloat16" else 2


def wait_all():
    import jax

    jax.effects_barrier()


def save(fname, handles, keys):
    if keys:
        _nd_utils.save(fname, dict(zip(keys, handles)))
    else:
        _nd_utils.save(fname, list(handles))


def load(fname):
    """Returns (names, arrays); names is [] for list-style containers."""
    data = _nd_utils.load(fname)
    if isinstance(data, dict):
        # container order (== save order; dicts preserve insertion) —
        # the reference ABI pairs names/arrays positionally
        names = list(data)
        return names, [data[k] for k in names]
    return [], list(data)


def list_ops():
    return sorted(_registry.OPS)


def _parse_value(s):
    """String -> typed hyper-parameter, the analogue of dmlc::Parameter
    parsing (numbers, bools, tuples; anything else stays a string)."""
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def invoke(op_name, inputs, keys, vals):
    params = {k: _parse_value(v) for k, v in zip(keys, vals)}
    out = _registry.invoke(op_name, list(inputs), params)
    return list(out) if isinstance(out, (list, tuple)) else [out]


# ---------------------------------------------------------------------------
# Symbol ABI (reference src/c_api/c_api_symbolic.cc)
# ---------------------------------------------------------------------------
class _PendingSymbol:
    """MXSymbolCreateAtomicSymbol result: an op + attrs awaiting
    MXSymbolCompose (the reference mutates the same handle on compose;
    the native layer swaps the stored PyObject)."""

    def __init__(self, op_name, attrs):
        self.op_name = op_name
        self.attrs = attrs


def symbol_create_variable(name):
    from .symbol import Variable

    return Variable(name)


def symbol_create_atomic(op_name, keys, vals):
    _registry.get_op(op_name)  # fail fast on unknown ops
    return _PendingSymbol(op_name,
                          {k: _parse_value(v) for k, v in zip(keys, vals)})


def symbol_compose(sym, name, keys, args):
    """Compose an atomic symbol with inputs.  ``keys`` names the inputs
    (may be empty for positional); returns the composed Symbol.

    Reference MXSymbolCompose semantics for the named form: unknown
    input names are an error, and inputs NOT supplied become free
    variables named ``<node>_<input>`` (how every reference frontend
    gets its auto-created ``fc1_weight``/``fc1_bias``)."""
    from .symbol import Variable, symbol as _sym_mod

    if not isinstance(sym, _PendingSymbol):
        raise TypeError("MXSymbolCompose target was already composed")
    args = list(args)
    if keys:
        opdef = _registry.get_op(sym.op_name)
        order = list(opdef.input_names)
        if not order:
            raise ValueError(
                "op %r does not declare input names; compose it "
                "positionally" % (sym.op_name,))
        unknown = [k for k in keys if k not in order]
        if unknown:
            raise ValueError("unknown input name(s) %s for op %r "
                             "(inputs: %s)"
                             % (unknown, sym.op_name, order))
        by_name = dict(zip(keys, args))
        node_name = name or _sym_mod._NameManager.get(
            sym.op_name.lower().lstrip("_"))
        args = [by_name.get(n) if n in by_name
                else Variable("%s_%s" % (node_name, n)) for n in order]
        name = node_name
    return _sym_mod._apply(sym.op_name, args, sym.attrs,
                           name=name or None)


def symbol_from_json(json_str):
    from .symbol import load_json

    return load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def symbol_infer_shape(sym, keys, ndims, flat_dims):
    """Flattened-CSR shape marshaling (reference MXSymbolInferShape):
    keys name the known args, ndims[i] dims each, concatenated in
    flat_dims.  Returns three (ndims, flat) pairs: args, outputs, aux."""
    shapes = {}
    pos = 0
    for k, nd_ in zip(keys, ndims):
        shapes[k] = tuple(int(d) for d in flat_dims[pos:pos + nd_])
        pos += nd_
    args, outs, auxs = sym.infer_shape_partial(**shapes)

    def flatten(shps):
        nds, flat = [], []
        for s in shps:
            s = s or ()
            nds.append(len(s))
            flat.extend(int(d) for d in s)
        return nds, flat

    return flatten(args) + flatten(outs) + flatten(auxs)


# ---------------------------------------------------------------------------
# Executor ABI (reference src/c_api/c_api_executor.cc)
# ---------------------------------------------------------------------------
_GRAD_REQ_FROM_CODE = {0: "null", 1: "write", 2: "add"}  # OpReqType


def executor_bind(sym, dev_type, dev_id, args, grads, req_codes, aux):
    names = sym.list_arguments()
    if len(args) != len(names):
        raise ValueError("bind got %d args for %d arguments %s"
                         % (len(args), len(names), names))
    reqs = [_GRAD_REQ_FROM_CODE.get(int(c), "null") for c in req_codes]
    arg_dict = dict(zip(names, args))
    grad_dict = {n: g for n, g, r in zip(names, grads, reqs)
                 if g is not None and r != "null"}
    req_dict = dict(zip(names, reqs))
    aux_names = sym.list_auxiliary_states()
    aux_dict = dict(zip(aux_names, aux)) if aux else None
    return sym.bind(ctx=_ctx(dev_type, dev_id), args=arg_dict,
                    args_grad=grad_dict or None, grad_req=req_dict,
                    aux_states=aux_dict)


def executor_forward(ex, is_train):
    # outputs are fetched separately via executor_outputs; building the
    # handle list here would be paid twice per step
    ex.forward(is_train=bool(is_train))


def executor_outputs(ex):
    return list(ex.outputs)


def executor_backward(ex, out_grads):
    ex.backward(out_grads=list(out_grads) if out_grads else None)


# ---------------------------------------------------------------------------
# KVStore ABI (reference src/c_api/c_api.cc MXKVStore*)
# ---------------------------------------------------------------------------
def kv_create(kv_type):
    from . import kvstore

    return kvstore.create(kv_type)


def kv_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def kv_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=priority)


def kv_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=priority)


def kv_rank(kv):
    return int(kv.rank)


def kv_num_workers(kv):
    return int(kv.num_workers)


# ---------------------------------------------------------------------------
# Autograd ABI (reference src/c_api/c_api_ndarray.cc MXAutograd*)
# ---------------------------------------------------------------------------
def autograd_set_recording(flag):
    from . import autograd

    return int(bool(autograd.set_recording(bool(flag))))


def autograd_set_training(flag):
    from . import autograd

    return int(bool(autograd.set_training(bool(flag))))


def autograd_mark_variables(arrays, grads):
    from . import autograd

    autograd.mark_variables(list(arrays), list(grads))


def autograd_backward(outputs, head_grads, retain_graph, train_mode):
    from . import autograd

    hg = list(head_grads) if head_grads else None
    autograd.backward(list(outputs), head_grads=hg,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))


def ndarray_get_grad(arr):
    if arr.grad is None:
        raise ValueError("array has no gradient buffer; call "
                         "MXAutogradMarkVariables first")
    return arr.grad


# ---------------------------------------------------------------------------
# DataIter ABI (reference src/c_api/c_api.cc MXDataIter* / MXListDataIters)
# ---------------------------------------------------------------------------
_DATA_ITERS = ("NDArrayIter", "CSVIter", "LibSVMIter", "MNISTIter",
               "ImageRecordIter")


def dataiter_list():
    return list(_DATA_ITERS)


class _DataIterHandle:
    """Iterator + current batch (the reference's DataIterHandle carries
    the same cursor semantics: Next() advances, Get*() read the current
    batch)."""

    def __init__(self, it):
        self.it = it
        self.batch = None

    def next(self):
        try:
            self.batch = next(self.it_iter)
            return True
        except StopIteration:
            self.batch = None
            return False

    def reset(self):
        self.it.reset()
        self.it_iter = iter(self.it)


def dataiter_create(name, keys, vals):
    from . import io as _io

    if name not in _DATA_ITERS:
        raise ValueError("unknown data iter %r (have %s)"
                         % (name, _DATA_ITERS))
    params = {k: _parse_value(v) for k, v in zip(keys, vals)}
    h = _DataIterHandle(getattr(_io, name)(**params))
    h.it_iter = iter(h.it)
    return h


def dataiter_next(h):
    return int(h.next())


def dataiter_before_first(h):
    h.reset()


def _current_batch(h):
    if h.batch is None:
        raise ValueError("no current batch: call MXDataIterNext first")
    return h.batch


def dataiter_get_data(h):
    return _current_batch(h).data[0]


def dataiter_get_label(h):
    return _current_batch(h).label[0]


def dataiter_get_pad(h):
    return int(_current_batch(h).pad or 0)
